// HighwayHash-256: bit-exact host implementation for bitrot checksums.
//
// The reference protects every shard block with HighwayHash256S keyed by the
// pi-derived magic key (/root/reference/cmd/bitrot.go:37,55-59).  This is a
// clean-room implementation of the public-domain HighwayHash algorithm
// (portable formulation); correctness is pinned by the reference's bitrot
// self-test vectors (cmd/bitrot.go:215-220) in tests/test_bitrot.py.
//
// Streaming API mirrors Go's hash.Hash: init/update/final, with final
// operating on a copy so the running state can keep accepting writes.

#include <cstdint>
#include <cstring>

#ifdef __AVX2__
#include <immintrin.h>
#endif

namespace {

struct HHState {
  uint64_t v0[4];
  uint64_t v1[4];
  uint64_t mul0[4];
  uint64_t mul1[4];
  uint8_t buf[32];
  uint32_t buflen;
};

const uint64_t kInit0[4] = {0xdbe6d5d5fe4cce2full, 0xa4093822299f31d0ull,
                            0x13198a2e03707344ull, 0x243f6a8885a308d3ull};
const uint64_t kInit1[4] = {0x3bd39e10cb0ef593ull, 0xc0acf169b5f18a8cull,
                            0xbe5466cf34e90c6cull, 0x452821e638d01377ull};

inline uint64_t Rot32(uint64_t x) { return (x >> 32) | (x << 32); }

inline uint64_t ReadLE64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/TPU VMs)
}

void Reset(HHState* s, const uint64_t key[4]) {
  for (int i = 0; i < 4; i++) {
    s->mul0[i] = kInit0[i];
    s->mul1[i] = kInit1[i];
    s->v0[i] = s->mul0[i] ^ key[i];
    s->v1[i] = s->mul1[i] ^ Rot32(key[i]);
  }
  s->buflen = 0;
}

inline void ZipperMergeAndAdd(const uint64_t v1, const uint64_t v0,
                              uint64_t* add1, uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ull) | (v1 & 0xff00000000ull)) >> 24) |
           (((v0 & 0xff0000000000ull) | (v1 & 0xff000000000000ull)) >> 16) |
           (v0 & 0xff0000ull) | ((v0 & 0xff00ull) << 32) |
           ((v1 & 0xff00000000000000ull) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ull) | (v0 & 0xff00000000ull)) >> 24) |
           (v1 & 0xff0000ull) | ((v1 & 0xff0000000000ull) >> 16) |
           ((v1 & 0xff00ull) << 24) | ((v0 & 0xff000000000000ull) >> 8) |
           ((v1 & 0xffull) << 48) | (v0 & 0xff00000000000000ull);
}

void Update(HHState* s, const uint64_t lanes[4]) {
  for (int i = 0; i < 4; i++) {
    s->v1[i] += s->mul0[i] + lanes[i];
    s->mul0[i] ^= (s->v1[i] & 0xffffffff) * (s->v0[i] >> 32);
    s->v0[i] += s->mul1[i];
    s->mul1[i] ^= (s->v0[i] & 0xffffffff) * (s->v1[i] >> 32);
  }
  ZipperMergeAndAdd(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  ZipperMergeAndAdd(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  ZipperMergeAndAdd(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  ZipperMergeAndAdd(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

inline void UpdatePacket(HHState* s, const uint8_t* packet) {
  uint64_t lanes[4] = {ReadLE64(packet), ReadLE64(packet + 8),
                       ReadLE64(packet + 16), ReadLE64(packet + 24)};
  Update(s, lanes);
}

#ifdef __AVX2__
// Vectorized bulk-packet loop: the four 64-bit lanes of each of
// v0/v1/mul0/mul1 live in one __m256i.  Bit-exact with Update() above —
// every scalar op maps 1:1 onto an AVX2 intrinsic, and the zipper-merge
// byte permutation becomes a PSHUFB with the mask derived from
// ZipperMergeAndAdd's masks/shifts (same constant as the public-domain
// highwayhash AVX2 formulation).  Verified against the scalar path and
// the reference's bitrot self-test vectors in tests/test_bitrot.py.
inline __m256i Zipper(__m256i x) {
  const __m256i mask = _mm256_set_epi64x(
      0x070806090D0A040BLL, 0x000F010E05020C03LL,
      0x070806090D0A040BLL, 0x000F010E05020C03LL);
  return _mm256_shuffle_epi8(x, mask);
}

void UpdatePacketsAVX2(HHState* s, const uint8_t* data, size_t npackets) {
  __m256i v0 = _mm256_loadu_si256((const __m256i*)s->v0);
  __m256i v1 = _mm256_loadu_si256((const __m256i*)s->v1);
  __m256i mul0 = _mm256_loadu_si256((const __m256i*)s->mul0);
  __m256i mul1 = _mm256_loadu_si256((const __m256i*)s->mul1);
  for (size_t i = 0; i < npackets; i++) {
    __m256i p = _mm256_loadu_si256((const __m256i*)(data + i * 32));
    v1 = _mm256_add_epi64(v1, _mm256_add_epi64(mul0, p));
    mul0 = _mm256_xor_si256(
        mul0, _mm256_mul_epu32(v1, _mm256_srli_epi64(v0, 32)));
    v0 = _mm256_add_epi64(v0, mul1);
    mul1 = _mm256_xor_si256(
        mul1, _mm256_mul_epu32(v0, _mm256_srli_epi64(v1, 32)));
    v0 = _mm256_add_epi64(v0, Zipper(v1));
    v1 = _mm256_add_epi64(v1, Zipper(v0));
  }
  _mm256_storeu_si256((__m256i*)s->v0, v0);
  _mm256_storeu_si256((__m256i*)s->v1, v1);
  _mm256_storeu_si256((__m256i*)s->mul0, mul0);
  _mm256_storeu_si256((__m256i*)s->mul1, mul1);
}
#endif

inline void UpdatePackets(HHState* s, const uint8_t* data, size_t npackets) {
#ifdef __AVX2__
  UpdatePacketsAVX2(s, data, npackets);
#else
  for (size_t i = 0; i < npackets; i++) UpdatePacket(s, data + i * 32);
#endif
}

void Rotate32By(uint32_t count, uint64_t lanes[4]) {
  for (int i = 0; i < 4; i++) {
    uint32_t half0 = (uint32_t)(lanes[i] & 0xffffffff);
    uint32_t half1 = (uint32_t)(lanes[i] >> 32);
    uint32_t r0 = count ? ((half0 << count) | (half0 >> (32 - count))) : half0;
    uint32_t r1 = count ? ((half1 << count) | (half1 >> (32 - count))) : half1;
    lanes[i] = (uint64_t)r0 | ((uint64_t)r1 << 32);
  }
}

void UpdateRemainder(HHState* s, const uint8_t* bytes, size_t size_mod32) {
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~(size_t)3);
  uint8_t packet[32] = {0};
  for (int i = 0; i < 4; i++)
    s->v0[i] += ((uint64_t)size_mod32 << 32) + size_mod32;
  Rotate32By((uint32_t)size_mod32, s->v1);
  for (size_t i = 0; i < (size_t)(remainder - bytes); i++) packet[i] = bytes[i];
  if (size_mod32 & 16) {
    for (int i = 0; i < 4; i++) packet[28 + i] = remainder[i + size_mod4 - 4];
  } else if (size_mod4) {
    packet[16 + 0] = remainder[0];
    packet[16 + 1] = remainder[size_mod4 >> 1];
    packet[16 + 2] = remainder[size_mod4 - 1];
  }
  UpdatePacket(s, packet);
}

void Permute(const uint64_t v[4], uint64_t* permuted) {
  permuted[0] = Rot32(v[2]);
  permuted[1] = Rot32(v[3]);
  permuted[2] = Rot32(v[0]);
  permuted[3] = Rot32(v[1]);
}

void ModularReduction(uint64_t a3_unmasked, uint64_t a2, uint64_t a1,
                      uint64_t a0, uint64_t* m1, uint64_t* m0) {
  uint64_t a3 = a3_unmasked & 0x3FFFFFFFFFFFFFFFull;
  *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
  *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

void Finalize256(HHState* s, uint64_t hash[4]) {
  for (int i = 0; i < 10; i++) {
    uint64_t permuted[4];
    Permute(s->v0, permuted);
    Update(s, permuted);
  }
  ModularReduction(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0],
                   s->v0[1] + s->mul0[1], s->v0[0] + s->mul0[0], &hash[1],
                   &hash[0]);
  ModularReduction(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2],
                   s->v0[3] + s->mul0[3], s->v0[2] + s->mul0[2], &hash[3],
                   &hash[2]);
}

}  // namespace

extern "C" {

int hh256_state_size(void) { return (int)sizeof(HHState); }

void hh256_init(void* state, const uint8_t key[32]) {
  uint64_t k[4] = {ReadLE64(key), ReadLE64(key + 8), ReadLE64(key + 16),
                   ReadLE64(key + 24)};
  Reset((HHState*)state, k);
}

void hh256_update(void* state, const uint8_t* data, size_t len) {
  HHState* s = (HHState*)state;
  if (s->buflen) {
    uint32_t need = 32 - s->buflen;
    uint32_t take = len < need ? (uint32_t)len : need;
    memcpy(s->buf + s->buflen, data, take);
    s->buflen += take;
    data += take;
    len -= take;
    if (s->buflen == 32) {
      UpdatePacket(s, s->buf);
      s->buflen = 0;
    }
  }
  size_t nfull = len / 32;
  if (nfull) {
    UpdatePackets(s, data, nfull);
    data += nfull * 32;
    len -= nfull * 32;
  }
  if (len) {
    memcpy(s->buf, data, len);
    s->buflen = (uint32_t)len;
  }
}

// Non-destructive finalize (state copied), matching Go hash.Hash.Sum.
void hh256_final(const void* state, uint8_t out[32]) {
  HHState s = *(const HHState*)state;
  if (s.buflen) UpdateRemainder(&s, s.buf, s.buflen);
  uint64_t h[4];
  Finalize256(&s, h);
  memcpy(out, h, 32);
}

// One-shot convenience.
void hh256_sum(const uint8_t key[32], const uint8_t* data, size_t len,
               uint8_t out[32]) {
  HHState s;
  uint64_t k[4] = {ReadLE64(key), ReadLE64(key + 8), ReadLE64(key + 16),
                   ReadLE64(key + 24)};
  Reset(&s, k);
  size_t nfull = len / 32;
  if (nfull) UpdatePackets(&s, data, nfull);
  if (len % 32) UpdateRemainder(&s, data + nfull * 32, len % 32);
  uint64_t h[4];
  Finalize256(&s, h);
  memcpy(out, h, 32);
}

// Batched: hash `count` independent streams laid out contiguously
// (stream i = data[i*stride : i*stride+len]); out 32 bytes each.
// This is the shard-block bitrot shape: many 128 KiB blocks per call.
void hh256_batch(const uint8_t* key, const uint8_t* data, size_t count,
                 size_t len, size_t stride, uint8_t* out) {
  for (size_t i = 0; i < count; i++)
    hh256_sum(key, data + i * stride, len, out + i * 32);
}

}  // extern "C"
