// GF(2^8) Reed-Solomon host codec with AVX2 PSHUFB nibble tables.
//
// This is the host-side equivalent of the reference's SIMD dependency
// (klauspost/reedsolomon, used from /root/reference/cmd/erasure-coding.go:63):
// multiplication by a constant c is two 16-entry table lookups (low/high
// nibble) XORed together; PSHUFB does 32 byte-lookups per instruction.
// Serves as (a) the CPU fallback codec when no TPU is attached and (b) the
// same-host AVX2 baseline that bench.py compares the TPU kernels against.
//
// Field: polynomial 0x11D, generator 2 — identical to minio_tpu.ops.gf256.

#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

uint8_t MUL[256][256];
uint8_t LOW_TBL[256][16];   // LOW_TBL[c][n]  = c * n
uint8_t HIGH_TBL[256][16];  // HIGH_TBL[c][n] = c * (n << 4)

struct TableInit {
  TableInit() {
    uint8_t exp[512];
    int log[256] = {0};
    int x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = (uint8_t)x;
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= 0x11D;
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    for (int a = 0; a < 256; a++)
      for (int b = 0; b < 256; b++)
        MUL[a][b] = (a && b) ? exp[log[a] + log[b]] : 0;
    for (int c = 0; c < 256; c++)
      for (int n = 0; n < 16; n++) {
        LOW_TBL[c][n] = MUL[c][n];
        HIGH_TBL[c][n] = MUL[c][n << 4];
      }
  }
} table_init;

inline void mul_acc_scalar(uint8_t c, const uint8_t* src, uint8_t* dst,
                           size_t n, bool first) {
  const uint8_t* row = MUL[c];
  if (first) {
    for (size_t i = 0; i < n; i++) dst[i] = row[src[i]];
  } else {
    for (size_t i = 0; i < n; i++) dst[i] ^= row[src[i]];
  }
}

#if defined(__AVX2__)
// dst ^= c * src (or dst = c * src when first), 32 bytes per step.
inline void mul_acc_avx2(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n,
                         bool first) {
  const __m256i lo_tbl = _mm256_broadcastsi128_si256(
      _mm_loadu_si128((const __m128i*)LOW_TBL[c]));
  const __m256i hi_tbl = _mm256_broadcastsi128_si256(
      _mm_loadu_si128((const __m128i*)HIGH_TBL[c]));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  size_t i = 0;
  for (; i + 32 <= n; i += 32) {
    __m256i v = _mm256_loadu_si256((const __m256i*)(src + i));
    __m256i lo = _mm256_and_si256(v, mask);
    __m256i hi = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
    __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(lo_tbl, lo),
                                    _mm256_shuffle_epi8(hi_tbl, hi));
    if (!first)
      prod = _mm256_xor_si256(prod, _mm256_loadu_si256((const __m256i*)(dst + i)));
    _mm256_storeu_si256((__m256i*)(dst + i), prod);
  }
  if (i < n) mul_acc_scalar(c, src + i, dst + i, n - i, first);
}
#endif

}  // namespace

extern "C" {

// out[r] = XOR_k mat[r*k + j] * src[j]   (all rows length `n`)
// mat: rows x k coefficients; src: k contiguous shards of n bytes;
// out: rows contiguous shards of n bytes.
//
// Column-tiled so each 16 KiB source/destination tile stays cache-hot
// across the whole coefficient matrix: every source byte is pulled from
// RAM once per call instead of `rows` times (the row-major loop's RAM
// traffic limited large batches to ~0.7 GiB/s on a ~2 GB/s-bandwidth
// host; klauspost/reedsolomon tiles the same way for the same reason).
void gf256_matmul(const uint8_t* mat, int rows, int k, const uint8_t* src,
                  uint8_t* out, size_t n) {
  const size_t TILE = 16384;
  bool started[256];
  for (size_t off = 0; off < n; off += TILE) {
    const size_t len = (n - off < TILE) ? (n - off) : TILE;
    for (int r = 0; r < rows; r++) started[r] = false;
    for (int j = 0; j < k; j++) {
      const uint8_t* s = src + (size_t)j * n + off;
      for (int r = 0; r < rows; r++) {
        uint8_t c = mat[r * k + j];
        if (c == 0) continue;
        uint8_t* dst = out + (size_t)r * n + off;
#if defined(__AVX2__)
        mul_acc_avx2(c, s, dst, len, !started[r]);
#else
        mul_acc_scalar(c, s, dst, len, !started[r]);
#endif
        started[r] = true;
      }
    }
    for (int r = 0; r < rows; r++)
      if (!started[r]) memset(out + (size_t)r * n + off, 0, len);
  }
}

// Batched (B, K, S) -> (B, rows, S) codec call: src is B contiguous
// blocks of k shards, out is B contiguous blocks of `rows` outputs.
// Looping blocks INSIDE one call matters beyond convenience: the Python
// caller marshals arguments and releases the GIL once per chunk instead
// of once per block — 128 ctypes round trips per 32-block batch convoyed
// the GIL against the etag-hasher and shard-writer threads and tripled
// the apparent encode time under load (ISSUE 5 pipeline).
void gf256_matmul_batch(const uint8_t* mat, int rows, int k,
                        const uint8_t* src, uint8_t* out, size_t n,
                        size_t nblocks) {
  for (size_t b = 0; b < nblocks; b++) {
    gf256_matmul(mat, rows, k, src + b * (size_t)k * n,
                 out + b * (size_t)rows * n, n);
  }
}

// Convenience single multiply: dst = c * src.
void gf256_mul(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n) {
#if defined(__AVX2__)
  mul_acc_avx2(c, src, dst, n, true);
#else
  mul_acc_scalar(c, src, dst, n, true);
#endif
}

int gf256_has_avx2(void) {
#if defined(__AVX2__)
  return 1;
#else
  return 0;
#endif
}

}  // extern "C"
