// Native S3 Select scan kernels: CSV structural scan + predicate masks +
// aggregates, and an NDJSON top-level-key scanner.
//
// This is the TPU-framework analogue of the reference's SIMD Select
// accelerators (internal/s3select/simdj/reader.go simdjson path and the
// generated-assembly CSV scanner behind select_benchmark_test.go): the
// hot loop — tokenize, extract needed fields, evaluate simple predicates,
// fold aggregates — runs in C++ at memory speed, while the Python driver
// (minio_tpu/select/native.py) keeps row-engine semantics by re-evaluating
// any block whose cells are AMBIGUOUS (values Python would coerce
// differently than the strict C parsers below: whitespace-padded numbers,
// "inf"/"nan", underscore digits, >2^53 ints, escaped quotes, JSON string
// escapes, non-canonical number text...).  Ambiguity is a per-call flag:
// correctness never depends on the fast path guessing.
//
// Layout contracts (all little-endian host):
//   starts/lens: int32 arrays of shape [ncols_needed][max_rows] (row-major
//   per column).  lens[r] == -1 => column missing in that row (null);
//   lens[r] == -2 => cell needs Python unquoting (contains doubled quote).
//   Otherwise [start, start+len) are the cell's logical bytes in buf
//   (surrounding CSV quotes stripped; trailing \r before \n stripped).
//
// Exposed via ctypes (see minio_tpu/select/native.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <cstdlib>
#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>

#if defined(__SSE2__)
#include <emmintrin.h>
#endif
#if defined(__AVX2__)
#include <immintrin.h>
#endif

// Persistent scan-thread pool.  The fused kernels used to spawn + join
// a std::thread per part on EVERY >=1 MiB block — thread creation that
// taxed small-object scans (a 1-2 MiB object paid several clone()s per
// Select).  Workers are detached process-lifetime daemons created on
// first demand (cap: FUSED_MAX_THREADS - 1); parts travel over a tiny
// condvar queue and each batch waits on its own stack latch, so the
// steady-state cost per block is one lock round per part, not a spawn.
namespace {

class ScanPool {
 public:
  static ScanPool &instance() {
    // heap singleton, intentionally leaked: a static-storage pool would
    // be DESTROYED at process exit while detached workers still wait on
    // its condvar (UB that hangs interpreter shutdown)
    static ScanPool *pool = new ScanPool();
    return *pool;
  }

  // Run fn(pi) for pi in [0, nt): parts 1..nt-1 go to the workers, the
  // calling thread runs part 0, and the call returns once every part
  // finished.  Latch lives on the caller's stack — no allocation.
  void run_parts(int nt, const std::function<void(int)> &fn) {
    struct Latch {
      std::mutex mu;
      std::condition_variable cv;
      int remaining;
    } latch;
    latch.remaining = nt - 1;
    {
      std::lock_guard<std::mutex> lk(qmu_);
      ensure_locked(nt - 1);
      for (int pi = 1; pi < nt; ++pi)
        q_.emplace_back([&fn, &latch, pi] {
          fn(pi);
          std::lock_guard<std::mutex> lk2(latch.mu);
          if (--latch.remaining == 0) latch.cv.notify_one();
        });
    }
    qcv_.notify_all();
    fn(0);
    std::unique_lock<std::mutex> lk(latch.mu);
    latch.cv.wait(lk, [&latch] { return latch.remaining == 0; });
  }

 private:
  void ensure_locked(int want) {
    while (nworkers_ < want && nworkers_ < kMaxWorkers) {
      ++nworkers_;
      std::thread(&ScanPool::worker, this).detach();
    }
  }

  void worker() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(qmu_);
        qcv_.wait(lk, [this] { return !q_.empty(); });
        task = std::move(q_.front());
        q_.pop_front();
      }
      task();
    }
  }

  static const int kMaxWorkers = 7;  // FUSED_MAX_THREADS - 1
  std::mutex qmu_;
  std::condition_variable qcv_;
  std::deque<std::function<void()>> q_;
  int nworkers_ = 0;
};

}  // namespace

extern "C" {

// ------------------------------------------------------------------ utils

// Find next byte equal to a or b in [p, end); returns end if none.
static inline const char *scan2(const char *p, const char *end,
                                char a, char b) {
#if defined(__SSE2__)
    const __m128i va = _mm_set1_epi8(a);
    const __m128i vb = _mm_set1_epi8(b);
    while (p + 16 <= end) {
        __m128i x = _mm_loadu_si128(reinterpret_cast<const __m128i *>(p));
        int m = _mm_movemask_epi8(
            _mm_or_si128(_mm_cmpeq_epi8(x, va), _mm_cmpeq_epi8(x, vb)));
        if (m)
            return p + __builtin_ctz(m);
        p += 16;
    }
#endif
    while (p < end && *p != a && *p != b)
        ++p;
    return p;
}

// Strict numeric parse matching the canonical subset of Python
// int()/float(): [+-]? (D+ | D+.D* | .D+) ([eE][+-]?D+)?
// Returns 1 and *out on success; 0 otherwise.  Cells with more than 15
// significant digits report failure (the caller treats them as
// ambiguous — Python compares big ints exactly, double cannot).
//
// Fast path: mantissa accumulated as uint64 (exact for <= 15 digits)
// scaled by an exact power of ten — one rounding, identical to strtod
// in this range (the classic Gay fast path).  Exponents outside |22|
// fall back to strtod for correct rounding.
static const double POW10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10,
    1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21,
    1e22};

// SWAR 8-digit block evaluator (Lemire): `raw` holds eight ASCII digits
// in memory order (first digit in the lowest byte).
static inline int all_digits8(uint64_t v) {
    return (((v & 0xF0F0F0F0F0F0F0F0ULL) |
             (((v + 0x0606060606060606ULL) & 0xF0F0F0F0F0F0F0F0ULL) >>
              4)) == 0x3333333333333333ULL);
}

static inline uint32_t eval8(uint64_t val) {
    const uint64_t mask = 0x000000FF000000FFULL;
    const uint64_t mul1 = 0x000F424000000064ULL;  // 100 + (1000000 << 32)
    const uint64_t mul2 = 0x0000271000000001ULL;  // 1 + (10000 << 32)
    val -= 0x3030303030303030ULL;
    val = (val * 10) + (val >> 8);
    val = (((val & mask) * mul1) + (((val >> 16) & mask) * mul2)) >> 32;
    return (uint32_t)val;
}

// op truth table over the 3-way compare c in {-1,0,1}: bit (c+1) of
// OPMASK[op].  ops: 0 '=', 1 '!=', 2 '<', 3 '<=', 4 '>', 5 '>='
static const int OPMASK[6] = {2, 5, 1, 3, 4, 6};

// Fast path for pure-integer cells of <= 8 digits.  REQUIRES 8 readable
// bytes at s (the Python driver pads every block with 8 slack bytes).
__attribute__((always_inline))
static inline int parse_int8_swar(const char *s, int32_t n, double *out) {
    uint64_t raw;
    memcpy(&raw, s, 8);
    if (n < 8)
        raw = (raw << ((8 - n) * 8)) |
              (0x3030303030303030ULL >> (n * 8));
    if (!all_digits8(raw))
        return 0;
    *out = (double)eval8(raw);
    return 1;
}

static inline int parse_num(const char *s, int32_t n, double *out) {
    if (n <= 0 || n >= 63)
        return 0;
    if (n <= 8 && parse_int8_swar(s, n, out))
        return 1;
    const char *p = s, *end = s + n;
    int neg = 0;
    if (*p == '+' || *p == '-') {
        neg = (*p == '-');
        ++p;
    }
    uint64_t mant = 0;
    int digits = 0;
    while (p < end && (unsigned char)(*p - '0') <= 9) {
        mant = mant * 10 + (unsigned char)(*p - '0');
        ++digits;
        ++p;
    }
    int total = digits;
    int exp10 = 0;
    if (p < end && *p == '.') {
        ++p;
        const char *fs = p;
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            mant = mant * 10 + (unsigned char)(*p - '0');
            ++p;
        }
        int fd = (int)(p - fs);
        total += fd;
        exp10 -= fd;
    }
    if (total == 0)
        return 0;
    if (total > 15)
        return 0;  // exact-int / long-mantissa territory: Python decides
    if (p < end && (*p == 'e' || *p == 'E')) {
        ++p;
        int eneg = 0;
        if (p < end && (*p == '+' || *p == '-')) {
            eneg = (*p == '-');
            ++p;
        }
        int ed = 0, ev = 0;
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            ev = ev * 10 + (*p - '0');
            if (ev > 400)
                ev = 400;
            ++ed;
            ++p;
        }
        if (!ed)
            return 0;
        exp10 += eneg ? -ev : ev;
    }
    if (p != end)
        return 0;
    double v;
    if (exp10 == 0) {
        v = (double)mant;
    } else if (exp10 > 0 && exp10 <= 22) {
        v = (double)mant * POW10[exp10];
    } else if (exp10 < 0 && exp10 >= -22) {
        v = (double)mant / POW10[-exp10];
    } else {
        // rare huge/tiny exponent: strtod for correct rounding
        char tmp[64];
        memcpy(tmp, s, n);
        tmp[n] = 0;
        char *ep = nullptr;
        v = strtod(tmp, &ep);
        if (ep != tmp + n)
            return 0;
        *out = v;  // strtod consumed the sign itself
        return 1;
    }
    *out = neg ? -v : v;
    return 1;
}

// Would Python's int()/float() possibly accept (or differently coerce)
// this cell even though parse_num rejected it?  Conservative: any cell
// starting with whitespace/sign/digit/dot/underscore/'i'/'n' (inf/nan)
// or a non-ASCII byte (unicode digits/whitespace), or ending with
// whitespace, is AMBIGUOUS and forces the block onto the Python path.
static int num_ambiguous(const char *s, int32_t n) {
    if (n <= 0)
        return 0;  // empty: Python rejects too => clean text
    unsigned char c0 = (unsigned char)s[0];
    unsigned char cl = (unsigned char)s[n - 1];
    if (c0 >= 0x80 || cl >= 0x80)
        return 1;
    if (c0 == ' ' || c0 == '\t' || cl == ' ' || cl == '\t')
        return 1;
    if (c0 == '+' || c0 == '-' || c0 == '.' || c0 == '_')
        return 1;
    if (c0 >= '0' && c0 <= '9')
        return 1;
    if (c0 == 'i' || c0 == 'I' || c0 == 'n' || c0 == 'N')
        return 1;
    return 0;
}

// UTF-8 aware LIKE matcher ('%' = any run, '_' = one codepoint).
// Pattern arrives pre-processed by Python: escape characters resolved
// into a literal-mask byte array (1 = literal byte, 0 = wildcard role).
static int utf8_next(const char *s, int i, int n) {
    ++i;
    while (i < n && ((unsigned char)s[i] & 0xC0) == 0x80)
        ++i;
    return i;
}

static int like_match(const char *s, int sn, const char *pat, int pn,
                      const unsigned char *lit) {
    // iterative glob with single-% backtracking (classic algorithm)
    int si = 0, pi = 0, star_p = -1, star_s = -1;
    while (si < sn) {
        if (pi < pn && !lit[pi] && pat[pi] == '%') {
            star_p = ++pi;
            star_s = si;
            continue;
        }
        if (pi < pn && !lit[pi] && pat[pi] == '_') {
            si = utf8_next(s, si, sn);
            ++pi;
            continue;
        }
        if (pi < pn && pat[pi] == s[si] &&
            (lit[pi] || (pat[pi] != '%' && pat[pi] != '_'))) {
            ++si;
            ++pi;
            continue;
        }
        if (star_p >= 0) {
            star_s = utf8_next(s, star_s, sn);
            si = star_s;
            pi = star_p;
            continue;
        }
        return 0;
    }
    while (pi < pn && !lit[pi] && pat[pi] == '%')
        ++pi;
    return pi == pn;
}

// -------------------------------------------------------------- CSV scan

// Quote-free fast scan: one linear SIMD pass extracting separator
// positions, constant work per separator.  Preconditions (checked by
// the caller): no quote byte anywhere in [buf, len).
static int64_t csv_scan_fast(const char *buf, int64_t len, char delim,
                             int final_block, const int32_t *col_idx,
                             int32_t ncols, int64_t max_rows,
                             int32_t *starts, int32_t *lens,
                             int32_t *row_start, int64_t *consumed) {
    int64_t row = 0;
    int32_t field = 0, k = 0;
    int64_t field_start = 0, row_begin = 0;
    int overflow = 0;
    const int32_t col0 = col_idx[0];
    const int single = (ncols == 1);
    for (int32_t c = 0; c < ncols; ++c)
        lens[(int64_t)c * max_rows] = -1;

    // handle() -> 0 normal, 1 stop (max_rows), 2 all needed cells of
    // this row captured (caller may skip remaining delimiters until the
    // next newline — a large win for wide rows)
    auto handle = [&](int64_t pos, int is_nl)
        __attribute__((always_inline)) {
        int captured = 0;
        if (single ? (field == col0)
                   : (k < ncols && col_idx[k] == field)) {
            int64_t ce = pos;
            if (is_nl && ce > field_start && buf[ce - 1] == '\r')
                --ce;
            starts[(int64_t)k * max_rows + row] = (int32_t)field_start;
            lens[(int64_t)k * max_rows + row] = (int32_t)(ce - field_start);
            ++k;
            captured = (k == ncols);
        }
        field_start = pos + 1;
        if (is_nl) {
            int64_t rl = pos - row_begin;
            if (rl == 0 || (rl == 1 && buf[row_begin] == '\r')) {
                // blank record: csv.reader (the row engine) skips it
                for (int32_t cc = 0; cc < k; ++cc)
                    lens[(int64_t)cc * max_rows + row] = -1;
                row_begin = pos + 1;
                field = 0;
                k = 0;
                return 0;
            }
            if (row_start)
                row_start[row] = (int32_t)row_begin;
            ++row;
            row_begin = pos + 1;  // consumed covers every counted row
            if (row >= max_rows) {
                overflow = 1;
                return 1;
            }
            for (int32_t cc = 0; cc < ncols; ++cc)
                lens[(int64_t)cc * max_rows + row] = -1;
            field = 0;
            k = 0;
            return 0;
        }
        ++field;
        return captured ? 2 : 0;
    };

    int64_t i = 0;
#if defined(__AVX2__)
    const __m256i vd = _mm256_set1_epi8(delim);
    const __m256i vn = _mm256_set1_epi8('\n');
    int skipping = 0;  // row's needed cells done: only newlines matter
    while (i + 32 <= len && !overflow) {
        __m256i x = _mm256_loadu_si256(
            reinterpret_cast<const __m256i *>(buf + i));
        uint32_t mn = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(x, vn));
        if (skipping && mn == 0) {
            i += 32;  // whole chunk is mid-row noise
            continue;
        }
        uint32_t m = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(x, vd)) | mn;
        if (skipping) {
            m &= ~(((uint32_t)1 << __builtin_ctz(mn)) - 1);
            skipping = 0;
        }
        while (m) {
            int b = __builtin_ctz(m);
            m &= m - 1;
            int rc = handle(i + b, (mn >> b) & 1);
            if (rc == 1)
                break;
            if (rc == 2) {
                // drop delimiter bits until the next newline
                uint32_t nn = mn & m;
                if (nn) {
                    m &= ~(((uint32_t)1 << __builtin_ctz(nn)) - 1);
                } else {
                    m = 0;
                    skipping = 1;
                }
                // field counting is moot while skipping: fields between
                // here and the newline are never needed (k == ncols)
            }
        }
        i += 32;
    }
    if (skipping) {
        // resume the scalar tail at the next newline
        const char *nlp = static_cast<const char *>(
            memchr(buf + i, '\n', len - i));
        i = nlp ? (nlp - buf) : len;
    }
#endif
    while (i < len && !overflow) {
        char c = buf[i];
        if (c == delim || c == '\n') {
            int rc = handle(i, c == '\n');
            if (rc == 1)
                break;
            if (rc == 2) {
                const char *nlp = static_cast<const char *>(
                    memchr(buf + i + 1, '\n', len - i - 1));
                if (nlp == nullptr) {
                    i = len;
                    break;
                }
                i = nlp - buf;
                continue;  // process the newline next iteration
            }
        }
        ++i;
    }
    if (overflow) {
        *consumed = row_begin;
        if (row_start)
            row_start[row] = (int32_t)row_begin;
        return row;  // complete rows so far; caller re-feeds the rest
    }
    *consumed = row_begin;
    if (final_block && row_begin < len) {
        int64_t rl = len - row_begin;
        if (rl == 0 || (rl == 1 && buf[row_begin] == '\r')) {
            *consumed = len;  // trailing blank: consumed, no record
        } else if (row < max_rows) {
            // trailing record without newline
            if (k < ncols && col_idx[k] == field) {
                starts[(int64_t)k * max_rows + row] =
                    (int32_t)field_start;
                lens[(int64_t)k * max_rows + row] =
                    (int32_t)(len - field_start);
            }
            if (row_start)
                row_start[row] = (int32_t)row_begin;
            ++row;
            *consumed = len;
        }
    }
    if (row_start)
        row_start[row] = (int32_t)(*consumed);
    return row;
}

// Structural scan of one block.  Returns the number of complete rows
// scanned (possibly fewer than the block holds when max_rows is hit —
// *consumed tells the caller where to resume), or -2 on an unterminated
// quote in the final block.
// *consumed = bytes of buf covered by the returned records.
int64_t sel_csv_scan(const char *buf, int64_t len, char delim, char quote,
                     int final_block,
                     const int32_t *col_idx, int32_t ncols,
                     int64_t max_rows,
                     int32_t *starts, int32_t *lens,
                     int32_t *row_start, int64_t *consumed) {
    if (memchr(buf, quote, len) == nullptr)
        return csv_scan_fast(buf, len, delim, final_block, col_idx, ncols,
                             max_rows, starts, lens, row_start, consumed);
    const char *p = buf, *end = buf + len;
    int64_t row = 0;
    *consumed = 0;
    while (p < end) {
        if (row >= max_rows)
            break;
        const char *rec = p;
        int32_t field = 0, k = 0;
        // pre-fill this row's needed columns as missing
        for (int32_t c = 0; c < ncols; ++c)
            lens[(int64_t)c * max_rows + row] = -1;
        int done_row = 0;
        while (!done_row) {
            int32_t cs, ce;  // logical cell extent
            int esc = 0;
            if (p < end && *p == quote) {
                ++p;
                const char *q = p;
                for (;;) {
                    const char *h = static_cast<const char *>(
                        memchr(q, quote, end - q));
                    if (!h) {
                        if (final_block)
                            return -2;  // unterminated quote
                        goto incomplete;
                    }
                    if (h + 1 < end && h[1] == quote) {
                        esc = 1;
                        q = h + 2;
                        continue;
                    }
                    if (h + 1 == end && !final_block)
                        goto incomplete;  // closing vs doubled: unknown
                    cs = (int32_t)(p - buf);
                    ce = (int32_t)(h - buf);
                    p = h + 1;
                    break;
                }
                // after closing quote: delimiter, newline, or EOF
                if (p < end && *p != delim && *p != '\n' && *p != '\r') {
                    // junk after quote: treat rest as part of the cell
                    const char *j = scan2(p, end, delim, '\n');
                    if (j == end && !final_block)
                        goto incomplete;
                    ce = (int32_t)(j - buf);
                    esc = 1;  // Python csv semantics differ: defer
                    p = j;
                }
            } else {
                const char *st = p;
                const char *j = scan2(p, end, delim, '\n');
                if (j == end && !final_block)
                    goto incomplete;
                cs = (int32_t)(st - buf);
                ce = (int32_t)(j - buf);
                if (ce > cs && buf[ce - 1] == '\r' &&
                    (j < end && *j == '\n'))
                    --ce;  // \r\n record delimiter
                p = j;
            }
            if (k < ncols && col_idx[k] == field) {
                starts[(int64_t)k * max_rows + row] = cs;
                lens[(int64_t)k * max_rows + row] =
                    esc ? -2 : (ce - cs);
                ++k;
            }
            ++field;
            if (p >= end) {
                if (!final_block)
                    goto incomplete;
                done_row = 1;  // final record without trailing newline
            } else if (*p == '\n') {
                ++p;
                done_row = 1;
            } else {
                ++p;  // delimiter
            }
        }
        {
            // blank record (empty line, or lone \r): csv.reader skips
            const char *rend = p;
            if (rend > rec && rend[-1] == '\n')
                --rend;
            int64_t rl = rend - rec;
            if (rl == 0 || (rl == 1 && *rec == '\r')) {
                for (int32_t cc = 0; cc < k; ++cc)
                    lens[(int64_t)cc * max_rows + row] = -1;
                *consumed = p - buf;
                continue;
            }
        }
        row_start[row] = (int32_t)(rec - buf);
        ++row;
        *consumed = p - buf;
        continue;
    incomplete:
        break;
    }
    row_start[row] = (int32_t)(*consumed);
    return row;
}

// --------------------------------------------------------- row emission

// Copy matched rows (verbatim, including their newline) into outbuf.
// Used for `SELECT * ... WHERE` over quote-free CSV when the output
// serialization matches the input (records pass through byte-exact).
// limit < 0 means unlimited.  Returns rows emitted; *out_len = bytes.
int64_t sel_emit_rows(const char *buf, const int32_t *row_start,
                      int64_t nrows, const uint8_t *mask, int64_t limit,
                      char *outbuf, int64_t *out_len) {
    int64_t n = 0, o = 0;
    for (int64_t r = 0; r < nrows; ++r) {
        if (mask && !mask[r])
            continue;
        if (limit >= 0 && n >= limit)
            break;
        int32_t a = row_start[r], b = row_start[r + 1];
        memcpy(outbuf + o, buf + a, b - a);
        o += b - a;
        if (b > a && outbuf[o - 1] != '\n')
            outbuf[o++] = '\n';  // final record without trailing newline
        ++n;
    }
    *out_len = o;
    return n;
}

// ------------------------------------------------- scalar cell functions
//
// The WHERE-leaf language extends to `fn(col) <op> literal` for the
// common scalar functions.  Transforms are exact for ASCII cells;
// anything containing a byte >= 0x80 (multibyte text whose case/space
// rules Python applies per codepoint) flags AMBIGUOUS so the block
// replays through the row engine — same contract as numeric parsing.
// fn codes: 0 none, 1 LOWER, 2 UPPER, 3 TRIM, 4 LTRIM, 5 RTRIM,
// 6 CHAR_LENGTH (cell becomes its codepoint count, compared
// numerically).
enum { FN_NONE = 0, FN_LOWER, FN_UPPER, FN_TRIM, FN_LTRIM, FN_RTRIM,
       FN_CHARLEN, FN_SUBSTR };
// FN_SUBSTR takes (start, len) via the fn_a/fn_b kernel params:
// Python s[max(start-1,0) : max(start-1,0)+len]; fb == -1 is the
// driver's 'no length' sentinel (slice to end) — explicit negative
// lengths never reach here (they fall back: Python-slice semantics).
// Codepoint indexing == byte indexing for the ASCII-only fast path.

static inline int all_ascii(const char *s, int32_t n) {
    for (int32_t i = 0; i < n; ++i)
        if ((unsigned char)s[i] >= 0x80)
            return 0;
    return 1;
}

// Python str.isspace() over ASCII: \t \n \v \f \r space AND the
// C0 separators \x1c-\x1f (str.strip() removes all of them)
static inline int py_space(char c) {
    unsigned char u = (unsigned char)c;
    return c == ' ' || (u >= 0x09 && u <= 0x0D) ||
           (u >= 0x1C && u <= 0x1F);
}

// Apply fn to [s, s+n) into scratch (capacity >= n).  Returns new
// length, or -1 when ambiguous (non-ASCII byte present).
static inline int32_t apply_fn(int fn, const char *s, int32_t n,
                               char *scratch, int32_t fa, int32_t fb) {
    if (!all_ascii(s, n))
        return -1;  // Python unicode semantics: replay
    const char *b = s, *e = s + n;
    switch (fn) {
    case FN_SUBSTR: {
        int32_t start0 = fa - 1;
        if (start0 < 0)
            start0 = 0;
        if (start0 > n)
            start0 = n;
        int32_t take = (fb < 0) ? (n - start0) : fb;
        if (take > n - start0)
            take = n - start0;
        if (take < 0)
            take = 0;
        memcpy(scratch, s + start0, take);
        return take;
    }
    case FN_TRIM:
    case FN_LTRIM:
        while (b < e && py_space(*b))
            ++b;
        if (fn == FN_LTRIM) {
            memcpy(scratch, b, e - b);
            return (int32_t)(e - b);
        }
        /* fallthrough for TRIM's right side */
        [[fallthrough]];
    case FN_RTRIM:
        if (fn == FN_RTRIM)
            b = s;
        while (e > b && py_space(e[-1]))
            --e;
        memcpy(scratch, b, e - b);
        return (int32_t)(e - b);
    case FN_LOWER:
        for (int32_t i = 0; i < n; ++i) {
            char c = s[i];
            scratch[i] = (c >= 'A' && c <= 'Z') ? (char)(c + 32) : c;
        }
        return n;
    case FN_UPPER:
        for (int32_t i = 0; i < n; ++i) {
            char c = s[i];
            scratch[i] = (c >= 'a' && c <= 'z') ? (char)(c - 32) : c;
        }
        return n;
    }
    memcpy(scratch, s, n);
    return n;
}

#define FN_SCRATCH 4096  // cells longer than this replay (rare)

// Comparison ops: 0 '=', 1 '!=', 2 '<', 3 '<=', 4 '>', 5 '>='
static inline int cmp_ok(int op, int c) {
    switch (op) {
    case 0: return c == 0;
    case 1: return c != 0;
    case 2: return c < 0;
    case 3: return c <= 0;
    case 4: return c > 0;
    case 5: return c >= 0;
    }
    return 0;
}

static inline int bytes_cmp(const char *a, int32_t an,
                            const char *b, int32_t bn) {
    int32_t n = an < bn ? an : bn;
    int c = n ? memcmp(a, b, n) : 0;
    if (c)
        return c < 0 ? -1 : 1;
    return an < bn ? -1 : (an > bn ? 1 : 0);
}

// Tiny per-cell numeric program for `expr(col) <op> literal` leaves
// where expr is an arithmetic/CAST chain over ONE column:
//   codes: 0 x+k, 1 x-k, 2 x*k, 3 x/k, 4 x%k (Python floor-sign mod),
//          5 k-x, 6 k/x, 7 trunc(x) (CAST INT), 8 noop (CAST FLOAT)
// A cell that fails the strict numeric parse is AMBIGUOUS (the row
// engine raises SQLError for arithmetic on non-numbers — the replay
// reproduces that exactly), as are div/mod by zero.
static inline int run_prog(double x, const int32_t *codes,
                           const double *ops, int plen, double *out) {
    for (int p = 0; p < plen; ++p) {
        double k = ops[p];
        switch (codes[p]) {
        case 0: x = x + k; break;
        case 1: x = x - k; break;
        case 2: x = x * k; break;
        case 3:
            if (k == 0.0)
                return 0;
            x = x / k;
            break;
        case 4: {
            if (k == 0.0)
                return 0;
            double r = fmod(x, k);
            if (r != 0.0 && ((r < 0.0) != (k < 0.0)))
                r += k;  // Python floor-sign modulo
            x = r;
            break;
        }
        case 5: x = k - x; break;
        case 6:
            if (x == 0.0)
                return 0;
            x = k / x;
            break;
        case 7: x = trunc(x); break;
        case 8: break;
        }
        // Exactness guard: beyond 2^53 the row engine's Python big-int
        // arithmetic diverges from doubles, and NaN/inf compare under
        // different rules (NaN cmp is always False in Python; the
        // 3-way compare here would read it as 'equal').  Both fail
        // this bound (NaN fails every comparison) => replay.
        if (!(x > -9007199254740992.0 && x < 9007199254740992.0))
            return 0;
    }
    *out = x;
    return 1;
}

// ---------------------------------------------------- per-cell leaf eval
//
// The array kernels (sel_cmp_num & co) and the fused one-pass kernels
// (sel_csv_agg_fused / sel_json_agg_fused) share these per-cell
// evaluators so the two paths cannot drift semantically.  Each returns
// the mask bit for one cell and bumps *amb for cells whose exact value
// Python must decide (the ambiguity-replay contract).

static inline int cell_cmp_num(const char *cs, int32_t l, int op,
                               int opmask, double num_lit,
                               const char *str_lit, int32_t str_len,
                               int fn, int32_t fn_a, int32_t fn_b,
                               char *scratch, int64_t *amb) {
    const char *s = cs;
    double v;
    if (fn == FN_CHARLEN) {
        if (l < 0) {
            if (l == -2)
                ++*amb;
            return 0;
        }
        if (!all_ascii(s, l)) {  // codepoint counting: Python decides
            ++*amb;
            return 0;
        }
        int c = ((double)l > num_lit) - ((double)l < num_lit);
        return (opmask >> (c + 1)) & 1;
    }
    if (fn != FN_NONE && l > 0) {
        if (l > FN_SCRATCH) {
            ++*amb;
            return 0;
        }
        int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
        if (nl < 0) {
            ++*amb;
            return 0;
        }
        s = scratch;
        l = nl;
    }
    // hot path: short pure-digit cell, fully inlined SWAR
    if ((uint32_t)(l - 1) < 8u && parse_int8_swar(s, l, &v)) {
        int c = (v > num_lit) - (v < num_lit);
        return (opmask >> (c + 1)) & 1;
    }
    if (l < 0) {
        if (l == -2)
            ++*amb;
        return 0;  // null (or needs-unquote: caller pre-screens)
    }
    if (parse_num(s, l, &v)) {
        int c = (v > num_lit) - (v < num_lit);
        return (opmask >> (c + 1)) & 1;
    }
    if (num_ambiguous(s, l)) {
        ++*amb;
        return 0;
    }
    return cmp_ok(op, bytes_cmp(s, l, str_lit, str_len));
}

static inline int cell_cmp_str(const char *cs, int32_t l, int op,
                               const char *lit, int32_t lit_len, int fn,
                               int32_t fn_a, int32_t fn_b, char *scratch,
                               int64_t *amb) {
    const char *s = cs;
    if (l < 0) {
        if (l == -2)
            ++*amb;
        return 0;
    }
    if (fn == FN_CHARLEN) {
        // text compare of the DECIMAL rendering of the length
        if (!all_ascii(s, l)) {
            ++*amb;
            return 0;
        }
        int32_t nl = (int32_t)snprintf(scratch, 16, "%d", l);
        s = scratch;
        l = nl;
    } else if (fn != FN_NONE && l > 0) {
        if (l > FN_SCRATCH) {
            ++*amb;
            return 0;
        }
        int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
        if (nl < 0) {
            ++*amb;
            return 0;
        }
        s = scratch;
        l = nl;
    }
    return cmp_ok(op, bytes_cmp(s, l, lit, lit_len));
}

static inline int cell_like(const char *cs, int32_t l, const char *pat,
                            int32_t pat_len, const unsigned char *lit,
                            int fn, int32_t fn_a, int32_t fn_b,
                            char *scratch, int64_t *amb) {
    const char *s = cs;
    if (l < 0) {
        if (l == -2)
            ++*amb;
        return 0;
    }
    if (fn != FN_NONE && l > 0) {
        if (l > FN_SCRATCH || fn == FN_CHARLEN) {
            ++*amb;
            return 0;
        }
        int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
        if (nl < 0) {
            ++*amb;
            return 0;
        }
        s = scratch;
        l = nl;
    }
    return like_match(s, l, pat, pat_len, lit);
}

static inline int cell_cmp_expr(const char *s, int32_t l, int opmask,
                                double num_lit, const int32_t *codes,
                                const double *ops, int plen,
                                int64_t *amb) {
    double v;
    // null/missing/garbage cells: the row engine RAISES for
    // arithmetic — replay the block so it can
    if (l < 0 || !parse_num(s, l, &v) ||
        !run_prog(v, codes, ops, plen, &v)) {
        ++*amb;
        return 0;
    }
    int c = (v > num_lit) - (v < num_lit);
    return (opmask >> (c + 1)) & 1;
}

// JSON variants over (type, extent) cells.  Type codes: 0 missing,
// 1 null, 2 false, 3 true, 4 number, 5 string, 6 ambiguous.

static inline int cell_json_cmp(const char *cs, int32_t l, uint8_t t,
                                int op, int opmask, double num_lit,
                                int lit_is_num, const char *str_lit,
                                int32_t str_len, int fn, int32_t fn_a,
                                int32_t fn_b, char *scratch,
                                int64_t *amb) {
    if (t == 0 || t == 1)  // missing/null: compare is false
        return 0;
    if (t == 6 || t == 2 || t == 3) {  // ambiguous or bool
        ++*amb;
        return 0;
    }
    const char *s = cs;
    if (fn != FN_NONE) {
        if (t != 5) {  // fn over a number cell: str() rendering
            ++*amb;
            return 0;
        }
        if (fn == FN_CHARLEN) {
            if (!all_ascii(s, l)) {
                ++*amb;
                return 0;
            }
            if (lit_is_num) {
                int c = ((double)l > num_lit) - ((double)l < num_lit);
                return (opmask >> (c + 1)) & 1;
            }
            int32_t nl = (int32_t)snprintf(scratch, 16, "%d", l);
            return cmp_ok(op, bytes_cmp(scratch, nl, str_lit, str_len));
        }
        if (l > FN_SCRATCH) {
            ++*amb;
            return 0;
        }
        int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
        if (nl < 0) {
            ++*amb;
            return 0;
        }
        s = scratch;
        l = nl;
    }
    double v;
    if (t == 4) {  // fn != NONE already returned above for t != 5
        if (!lit_is_num) {  // text compare of number cell: rendering
            ++*amb;
            return 0;
        }
        if (!parse_num(s, l, &v)) {  // huge ints etc.
            ++*amb;
            return 0;
        }
        int c = v < num_lit ? -1 : (v > num_lit ? 1 : 0);
        return cmp_ok(op, c);
    }
    // string cell
    if (lit_is_num && parse_num(s, l, &v)) {
        int c = v < num_lit ? -1 : (v > num_lit ? 1 : 0);
        return cmp_ok(op, c);
    }
    if (lit_is_num && num_ambiguous(s, l)) {
        ++*amb;
        return 0;
    }
    return cmp_ok(op, bytes_cmp(s, l, str_lit, str_len));
}

static inline int cell_json_like(const char *cs, int32_t l, uint8_t t,
                                 const char *pat, int32_t pat_len,
                                 const unsigned char *lit, int fn,
                                 int32_t fn_a, int32_t fn_b,
                                 char *scratch, int64_t *amb) {
    if (t == 0 || t == 1)
        return 0;
    if (t != 5) {
        ++*amb;
        return 0;
    }
    const char *s = cs;
    if (fn != FN_NONE) {
        if (l > FN_SCRATCH || fn == FN_CHARLEN) {
            ++*amb;
            return 0;
        }
        int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
        if (nl < 0) {
            ++*amb;
            return 0;
        }
        s = scratch;
        l = nl;
    }
    return like_match(s, l, pat, pat_len, lit);
}

static inline int cell_json_isnull(int32_t l, uint8_t t, int64_t *amb) {
    if (t == 6) {
        ++*amb;
        return 0;
    }
    return t == 0 || t == 1 || (t == 5 && l == 0);
}

static inline int cell_json_cmp_expr(const char *s, int32_t l, uint8_t t,
                                     int opmask, double num_lit,
                                     const int32_t *codes,
                                     const double *ops, int plen,
                                     int64_t *amb) {
    double v;
    // number tokens and numeric strings both feed arithmetic in
    // the row engine (_num coerces); everything else raises there
    if ((t != 4 && t != 5) || !parse_num(s, l, &v) ||
        !run_prog(v, codes, ops, plen, &v)) {
        ++*amb;
        return 0;
    }
    int c = (v > num_lit) - (v < num_lit);
    return (opmask >> (c + 1)) & 1;
}

// Numeric-literal comparison leaf: cells that parse numerically compare
// against num_lit; everything else (including empty) compares textually
// against str_lit, replicating sql._cmp_pair.  Returns count of
// AMBIGUOUS cells (0 => mask is exact).
int64_t sel_cmp_num(const char *buf, const int32_t *starts,
                    const int32_t *lens, int64_t n, int op,
                    double num_lit, const char *str_lit, int32_t str_len,
                    uint8_t *mask, int fn, int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    const int opmask = OPMASK[op];
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_cmp_num(buf + starts[i], lens[i], op,
                                        opmask, num_lit, str_lit, str_len,
                                        fn, fn_a, fn_b, scratch, &amb);
    return amb;
}

// Text-literal comparison leaf: pure byte compare (UTF-8 order == code
// point order).  Cells are never ambiguous here except -2 (unquote).
int64_t sel_cmp_str(const char *buf, const int32_t *starts,
                    const int32_t *lens, int64_t n, int op,
                    const char *lit, int32_t lit_len, uint8_t *mask,
                    int fn, int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_cmp_str(buf + starts[i], lens[i], op,
                                        lit, lit_len, fn, fn_a, fn_b,
                                        scratch, &amb);
    return amb;
}

// LIKE leaf.  negate handled by the Python driver (needs the valid
// mask).  lit[] marks pattern bytes that are literals (escape-resolved).
int64_t sel_like(const char *buf, const int32_t *starts,
                 const int32_t *lens, int64_t n,
                 const char *pat, int32_t pat_len,
                 const unsigned char *lit, uint8_t *mask, int fn,
                 int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_like(buf + starts[i], lens[i], pat,
                                     pat_len, lit, fn, fn_a, fn_b,
                                     scratch, &amb);
    return amb;
}

// Validity mask: 1 where the cell exists (len >= 0).  -2 counts as
// existing but ambiguous.
void sel_valid(const int32_t *lens, int64_t n, uint8_t *mask) {
    for (int64_t i = 0; i < n; ++i)
        mask[i] = lens[i] >= 0 || lens[i] == -2;
}

// IS NULL mask: missing column or empty text (row engine: None or "").
void sel_isnull(const int32_t *lens, int64_t n, uint8_t *mask) {
    for (int64_t i = 0; i < n; ++i)
        mask[i] = lens[i] == -1 || lens[i] == 0;
}

// Aggregate fold over one column under an optional row mask.
// agg op: 0 COUNT, 1 SUM/AVG, 2 MIN/MAX (tracks argmin/argmax).
// Returns count of cells folded; *amb counts ambiguous cells (caller
// re-runs the block in Python when nonzero).  For SUM a non-numeric
// non-empty cell is ambiguous (the row engine raises SQLError — the
// Python replay reproduces that exactly).
int64_t sel_agg(const char *buf, const int32_t *starts,
                const int32_t *lens, int64_t n, const uint8_t *mask,
                int what, double *sum, double *minv, double *maxv,
                int64_t *argmin, int64_t *argmax, int64_t *amb) {
    int64_t cnt = 0;
    *amb = 0;
    double s = 0.0;
    double lo = 0.0, hi = 0.0;
    int64_t ilo = -1, ihi = -1;
    for (int64_t i = 0; i < n; ++i) {
        if (mask && !mask[i])
            continue;
        int32_t l = lens[i];
        if (l == -1 || l == 0)
            continue;  // null/empty: skipped by accumulate
        if (l == -2) {
            ++*amb;
            continue;
        }
        if (what == 0) {
            ++cnt;
            continue;
        }
        double v;
        if (!parse_num(buf + starts[i], l, &v)) {
            ++*amb;  // SUM raises / MIN-MAX mixes text: Python decides
            continue;
        }
        ++cnt;
        if (what == 1) {
            s += v;
        } else {
            if (ilo < 0 || v < lo) {
                lo = v;
                ilo = i;
            }
            if (ihi < 0 || v > hi) {
                hi = v;
                ihi = i;
            }
        }
    }
    *sum = s;
    *minv = lo;
    *maxv = hi;
    *argmin = ilo;
    *argmax = ihi;
    return cnt;
}

// ------------------------------------------------ fused one-pass kernels
//
// sel_csv_agg_fused: structural scan + WHERE program + aggregate fold in
// ONE traversal of a quote-free block (the caller guarantees no quote
// byte — the same precondition as csv_scan_fast).  No per-row index
// arrays are materialized: a row's needed cells live in registers/L1
// between the scan and the predicate, which is what closes the
// narrow-row perf letter (the multi-pass path wrote ~12 B of starts/
// lens per 17-B row and then re-walked them per predicate leaf).
//
// WHERE program: leaves described by parallel arrays (kind, slot, op,
// fn, fa, fb, num, aux offset/len into blob/likemask or the expr code/
// operand pools), composed by a postfix `prog`: entry >= 0 pushes leaf
// [entry]; -1 AND, -2 OR, -3 NOT.  Leaf kinds: 0 cmp-num, 1 cmp-str,
// 2 LIKE, 3 IS NULL, 4 valid, 5 expr-prog.  Aggregates: agg_what 0
// COUNT, 1 SUM/AVG, 2 MIN/MAX; agg_slot -1 = COUNT(*).  MIN/MAX report
// the winning cell's extent so the driver can coerce its exact text.
//
// Ambiguity contract unchanged: any ambiguous cell bumps *amb_out and
// the driver replays the whole consumed region through the row engine
// (so once amb != 0 the kernel skips predicate/aggregate work and only
// finishes the structural scan for *consumed).

#define FUSED_MAX_COLS 16
#define FUSED_MAX_STACK 64
#define FUSED_MAX_AGGS 16
#define FUSED_MAX_THREADS 8

// Scan parallelism (the reference's simdj reader also fans block
// parsing across goroutines): blocks >= 1 MiB split at newline
// boundaries across up to hardware_concurrency (cap 4) threads.
// MINIO_TPU_SELECT_THREADS=1 pins it single-threaded.
static int fused_threads() {
    static const int t = [] {
        const char *e = getenv("MINIO_TPU_SELECT_THREADS");
        if (e && *e) {
            int v = atoi(e);
            if (v >= 1)
                return v > FUSED_MAX_THREADS ? FUSED_MAX_THREADS : v;
        }
        unsigned hc = std::thread::hardware_concurrency();
        // mild oversubscription (4 scan threads even on 2 cores) rides
        // out scheduler throttling in quota-bound containers; threads
        // are short-lived and split work statically, so the only cost
        // is a couple of extra spawns per >=1 MiB block
        return (int)(hc >= 2 ? 4 : 1);
    }();
    return t;
}

// Per-thread partial aggregate state + its exact merge.  COUNT/SUM add
// (SUM merge is the same float block-merge the per-block driver commit
// already performs); MIN/MAX keep the FIRST occurrence on ties (strict
// compare, parts merged in byte order) so the reported cell extent is
// the one the sequential scan would have picked.
struct FusedPart {
    int64_t cnt[FUSED_MAX_AGGS];
    double sum[FUSED_MAX_AGGS], mn[FUSED_MAX_AGGS], mx[FUSED_MAX_AGGS];
    int32_t mnp[FUSED_MAX_AGGS], mnl[FUSED_MAX_AGGS];
    int32_t mxp[FUSED_MAX_AGGS], mxl[FUSED_MAX_AGGS];
    int64_t rows, amb, cons, qhit;
};

static void fused_merge(const FusedPart *parts, const int64_t *base,
                        int nt, int32_t naggs,
                        int64_t *agg_count, double *agg_sum,
                        double *agg_min, double *agg_max,
                        int32_t *agg_minpos, int32_t *agg_minlen,
                        int32_t *agg_maxpos, int32_t *agg_maxlen,
                        int64_t *rows_out, int64_t *amb_out) {
    int64_t rows = 0, amb = 0;
    for (int32_t a = 0; a < naggs; ++a) {
        agg_count[a] = 0;
        agg_sum[a] = 0.0;
        agg_min[a] = agg_max[a] = 0.0;
        agg_minpos[a] = agg_maxpos[a] = 0;
        agg_minlen[a] = agg_maxlen[a] = -1;
    }
    for (int pi = 0; pi < nt; ++pi) {
        const FusedPart &P = parts[pi];
        rows += P.rows;
        amb += P.amb;
        for (int32_t a = 0; a < naggs; ++a) {
            agg_count[a] += P.cnt[a];
            agg_sum[a] += P.sum[a];
            if (P.mnl[a] >= 0 &&
                (agg_minlen[a] < 0 || P.mn[a] < agg_min[a])) {
                agg_min[a] = P.mn[a];
                agg_minpos[a] = (int32_t)(P.mnp[a] + base[pi]);
                agg_minlen[a] = P.mnl[a];
            }
            if (P.mxl[a] >= 0 &&
                (agg_maxlen[a] < 0 || P.mx[a] > agg_max[a])) {
                agg_max[a] = P.mx[a];
                agg_maxpos[a] = (int32_t)(P.mxp[a] + base[pi]);
                agg_maxlen[a] = P.mxl[a];
            }
        }
    }
    *rows_out = rows;
    *amb_out = amb;
}

// Newline-aligned split points for a T-way parallel scan; returns the
// part count (1 = don't parallelize).  cut[0] = 0, cut[nt] = len, and
// every interior cut lands just past a '\n' so parts hold whole rows.
static int fused_cuts(const char *buf, int64_t len, int T,
                      int64_t *cut) {
    int nt = 1;
    cut[0] = 0;
    for (int t = 1; t < T && nt < FUSED_MAX_THREADS; ++t) {
        int64_t target = len * t / T;
        if (target <= cut[nt - 1])
            continue;
        const char *nl = static_cast<const char *>(
            memchr(buf + target, '\n', len - target));
        if (!nl)
            break;
        int64_t c = (nl - buf) + 1;
        if (c >= len || c <= cut[nt - 1])
            continue;
        cut[nt++] = c;
    }
    cut[nt] = len;
    return nt;
}

static int64_t csv_agg_fused_part(
    const char *buf, int64_t len, char delim, char quote,
    int final_block, const int32_t *col_idx, int32_t ncols,
    int32_t nleaves, const int32_t *lf_kind, const int32_t *lf_slot,
    const int32_t *lf_op, const int32_t *lf_fn, const int32_t *lf_fa,
    const int32_t *lf_fb, const double *lf_num, const int32_t *lf_aoff,
    const int32_t *lf_alen, const char *blob,
    const unsigned char *likemask, const int32_t *prog, int32_t prog_len,
    const int32_t *expr_codes, const double *expr_ops,
    int32_t naggs, const int32_t *agg_what, const int32_t *agg_slot,
    int64_t *agg_count, double *agg_sum, double *agg_min, double *agg_max,
    int32_t *agg_minpos, int32_t *agg_minlen,
    int32_t *agg_maxpos, int32_t *agg_maxlen,
    int64_t *rows_out, int64_t *amb_out, int64_t *consumed,
    int64_t *qhit) {
    int64_t row = 0, amb = 0;
    int qstop = 0;  // quote seen: stop before the row containing it
    int32_t cp[FUSED_MAX_COLS], cl[FUSED_MAX_COLS];
    char scratch[FN_SCRATCH];
    for (int32_t c = 0; c < ncols; ++c)
        cl[c] = -1;
    for (int32_t a = 0; a < naggs; ++a) {
        agg_count[a] = 0;
        agg_sum[a] = 0.0;
        agg_min[a] = agg_max[a] = 0.0;
        agg_minpos[a] = agg_maxpos[a] = 0;
        agg_minlen[a] = agg_maxlen[a] = -1;
    }
    int32_t field = 0, k = 0;
    int64_t field_start = 0, row_begin = 0;
    const int32_t col0 = col_idx[0];
    const int single = (ncols == 1);
    // specialize the overwhelmingly common program shape — one numeric
    // comparison leaf feeding COUNT(*) — so the per-row path is a SWAR
    // parse + compare + increment with no interpreter dispatch at all
    const int simple_cmp =
        nleaves == 1 && prog_len == 1 && lf_kind[0] == 0 &&
        lf_fn[0] == 0;
    const int count_star_only =
        naggs == 1 && agg_slot[0] < 0;
    const int s_opmask = simple_cmp ? OPMASK[lf_op[0]] : 0;
    const double s_num = simple_cmp ? lf_num[0] : 0.0;
    const int32_t s_slot = simple_cmp ? lf_slot[0] : 0;

    // kept out of line: the generic program interpreter must not bloat
    // the per-separator scan loop's inline expansion
    auto eval_row_slow = [&]() __attribute__((noinline)) {
        int ok = 1;
        if (nleaves) {
            uint8_t st[FUSED_MAX_STACK];
            int sp = 0;
            for (int32_t pi = 0; pi < prog_len; ++pi) {
                int32_t e = prog[pi];
                if (e >= 0) {
                    const int32_t sl = lf_slot[e];
                    const char *s = buf + cp[sl];
                    const int32_t l = cl[sl];
                    int r;
                    switch (lf_kind[e]) {
                    case 0:
                        r = cell_cmp_num(s, l, lf_op[e], OPMASK[lf_op[e]],
                                         lf_num[e], blob + lf_aoff[e],
                                         lf_alen[e], lf_fn[e], lf_fa[e],
                                         lf_fb[e], scratch, &amb);
                        break;
                    case 1:
                        r = cell_cmp_str(s, l, lf_op[e],
                                         blob + lf_aoff[e], lf_alen[e],
                                         lf_fn[e], lf_fa[e], lf_fb[e],
                                         scratch, &amb);
                        break;
                    case 2:
                        r = cell_like(s, l, blob + lf_aoff[e],
                                      lf_alen[e], likemask + lf_aoff[e],
                                      lf_fn[e], lf_fa[e], lf_fb[e],
                                      scratch, &amb);
                        break;
                    case 3:  // IS NULL (fast path never sees -2)
                        r = (l == -1 || l == 0);
                        break;
                    case 4:  // valid
                        r = (l >= 0 || l == -2);
                        break;
                    default:  // 5: expr program
                        r = cell_cmp_expr(s, l, OPMASK[lf_op[e]],
                                          lf_num[e],
                                          expr_codes + lf_aoff[e],
                                          expr_ops + lf_aoff[e],
                                          lf_alen[e], &amb);
                    }
                    st[sp++] = (uint8_t)r;
                } else if (e == -1) {
                    st[sp - 2] &= st[sp - 1];
                    --sp;
                } else if (e == -2) {
                    st[sp - 2] |= st[sp - 1];
                    --sp;
                } else {
                    st[sp - 1] ^= 1;
                }
            }
            ok = st[0];
        }
        if (!ok || amb)
            return;
        for (int32_t a = 0; a < naggs; ++a) {
            const int32_t sl = agg_slot[a];
            if (sl < 0) {  // COUNT(*)
                ++agg_count[a];
                continue;
            }
            const int32_t l = cl[sl];
            if (l == -1 || l == 0)
                continue;  // null/empty: skipped by accumulate
            if (agg_what[a] == 0) {
                ++agg_count[a];
                continue;
            }
            double v;
            if (!parse_num(buf + cp[sl], l, &v)) {
                ++amb;  // SUM raises / MIN-MAX mixes text: Python decides
                continue;
            }
            ++agg_count[a];
            if (agg_what[a] == 1) {
                agg_sum[a] += v;
            } else {
                if (agg_minlen[a] < 0 || v < agg_min[a]) {
                    agg_min[a] = v;
                    agg_minpos[a] = cp[sl];
                    agg_minlen[a] = l;
                }
                if (agg_maxlen[a] < 0 || v > agg_max[a]) {
                    agg_max[a] = v;
                    agg_maxpos[a] = cp[sl];
                    agg_maxlen[a] = l;
                }
            }
        }
    };

    auto eval_row = [&]() __attribute__((always_inline)) {
        if (amb)
            return;  // block will replay: scan only
        if (count_star_only && nleaves == 0) {
            ++agg_count[0];
            return;
        }
        if (simple_cmp && count_star_only) {
            const int32_t l = cl[s_slot];
            const char *s = buf + cp[s_slot];
            double v;
            if ((uint32_t)(l - 1) < 8u && parse_int8_swar(s, l, &v)) {
                int c = (v > s_num) - (v < s_num);
                agg_count[0] += (s_opmask >> (c + 1)) & 1;
                return;
            }
            agg_count[0] += cell_cmp_num(
                s, l, lf_op[0], s_opmask, s_num, blob + lf_aoff[0],
                lf_alen[0], 0, 0, 0, scratch, &amb) && !amb;
            return;
        }
        eval_row_slow();
    };

    // handle() -> 0 normal, 2 all needed cells of this row captured
    // (caller may skip remaining delimiters until the next newline)
    auto handle = [&](int64_t pos, int is_nl)
        __attribute__((always_inline)) {
        if (single ? (field == col0)
                   : (k < ncols && col_idx[k] == field)) {
            int64_t ce = pos;
            if (is_nl && ce > field_start && buf[ce - 1] == '\r')
                --ce;
            cp[k] = (int32_t)field_start;
            cl[k] = (int32_t)(ce - field_start);
            ++k;
        }
        field_start = pos + 1;
        if (is_nl) {
            int64_t rl = pos - row_begin;
            if (!(rl == 0 || (rl == 1 && buf[row_begin] == '\r'))) {
                // blank records are skipped like csv.reader does
                eval_row();
                ++row;
            }
            row_begin = pos + 1;
            for (int32_t c = 0; c < k; ++c)
                cl[c] = -1;
            field = 0;
            k = 0;
            return 0;
        }
        ++field;
        return (k == ncols) ? 2 : 0;
    };

    // Quote handling is fused into the scan (no separate memchr pass —
    // at narrow-row rates an extra memory pass costs as much as the
    // scan): the first quote byte stops the kernel BEFORE the row
    // containing it, *qhit tells the driver to route the quoted
    // stretch through the array kernels, and scanning resumes fused on
    // the next block.
    int64_t i = 0;
#if defined(__AVX2__)
    const __m256i vd = _mm256_set1_epi8(delim);
    const __m256i vn = _mm256_set1_epi8('\n');
    const __m256i vq = _mm256_set1_epi8(quote);
    int skipping = 0;  // row's needed cells done: only newlines matter
    while (i + 32 <= len && !qstop) {
        __m256i x = _mm256_loadu_si256(
            reinterpret_cast<const __m256i *>(buf + i));
        uint32_t mn = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(x, vn));
        uint32_t mq = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(x, vq));
        // process only separator bits strictly before the first quote
        const uint32_t limit =
            mq ? (((uint32_t)1 << __builtin_ctz(mq)) - 1) : 0xFFFFFFFFu;
        mn &= limit;
        uint32_t m;
        if (skipping) {
            if (mn == 0) {
                if (mq) {
                    qstop = 1;
                    break;
                }
                i += 32;  // whole chunk is mid-row noise
                continue;
            }
            m = ((uint32_t)_mm256_movemask_epi8(
                     _mm256_cmpeq_epi8(x, vd)) | mn) & limit;
            m &= ~(((uint32_t)1 << __builtin_ctz(mn)) - 1);
            skipping = 0;
        } else {
            m = ((uint32_t)_mm256_movemask_epi8(
                     _mm256_cmpeq_epi8(x, vd)) | mn) & limit;
        }
        while (m) {
            int b = __builtin_ctz(m);
            m &= m - 1;
            if (handle(i + b, (mn >> b) & 1) == 2) {
                // drop delimiter bits until the next newline
                uint32_t nn = mn & m;
                if (nn) {
                    m &= ~(((uint32_t)1 << __builtin_ctz(nn)) - 1);
                } else {
                    m = 0;
                    skipping = 1;
                }
            }
        }
        if (mq) {
            qstop = 1;
            break;
        }
        i += 32;
    }
    if (skipping && !qstop) {
        // resume the scalar tail at the next newline (or quote)
        const char *z = scan2(buf + i, buf + len, quote, '\n');
        if (z == buf + len)
            i = len;
        else if (*z == quote)
            qstop = 1;
        else
            i = z - buf;
    }
#endif
    while (i < len && !qstop) {
        char c = buf[i];
        if (c == quote) {
            qstop = 1;
            break;
        }
        if (c == delim || c == '\n') {
            if (handle(i, c == '\n') == 2) {
                const char *z = scan2(buf + i + 1, buf + len, quote,
                                      '\n');
                if (z == buf + len) {
                    i = len;
                    break;
                }
                if (*z == quote) {
                    qstop = 1;
                    break;
                }
                i = z - buf;
                continue;  // process the newline next iteration
            }
        }
        ++i;
    }
    *consumed = row_begin;
    if (final_block && !qstop && row_begin < len) {
        int64_t rl = len - row_begin;
        if (rl == 0 || (rl == 1 && buf[row_begin] == '\r')) {
            *consumed = len;  // trailing blank: consumed, no record
        } else {
            // trailing record without newline
            if (k < ncols && col_idx[k] == field) {
                cp[k] = (int32_t)field_start;
                cl[k] = (int32_t)(len - field_start);
            }
            eval_row();
            ++row;
            *consumed = len;
        }
    }
    *rows_out = row;
    *amb_out = amb;
    *qhit = qstop;
    return row;
}

int64_t sel_csv_agg_fused(
    const char *buf, int64_t len, char delim, char quote,
    int final_block, const int32_t *col_idx, int32_t ncols,
    int32_t nleaves, const int32_t *lf_kind, const int32_t *lf_slot,
    const int32_t *lf_op, const int32_t *lf_fn, const int32_t *lf_fa,
    const int32_t *lf_fb, const double *lf_num, const int32_t *lf_aoff,
    const int32_t *lf_alen, const char *blob,
    const unsigned char *likemask, const int32_t *prog, int32_t prog_len,
    const int32_t *expr_codes, const double *expr_ops,
    int32_t naggs, const int32_t *agg_what, const int32_t *agg_slot,
    int64_t *agg_count, double *agg_sum, double *agg_min, double *agg_max,
    int32_t *agg_minpos, int32_t *agg_minlen,
    int32_t *agg_maxpos, int32_t *agg_maxlen,
    int64_t *rows_out, int64_t *amb_out, int64_t *consumed,
    int64_t *saw_quote) {
    const int T = fused_threads();
    if (T > 1 && len >= (1 << 20) && naggs <= FUSED_MAX_AGGS) {
        int64_t cut[FUSED_MAX_THREADS + 1];
        const int nt = fused_cuts(buf, len, T, cut);
        if (nt > 1) {
            FusedPart parts[FUSED_MAX_THREADS];
            auto runp = [&](int pi, int fin) {
                FusedPart &P = parts[pi];
                csv_agg_fused_part(
                    buf + cut[pi], cut[pi + 1] - cut[pi], delim, quote,
                    fin, col_idx, ncols, nleaves, lf_kind, lf_slot,
                    lf_op, lf_fn, lf_fa, lf_fb, lf_num, lf_aoff,
                    lf_alen, blob, likemask, prog, prog_len, expr_codes,
                    expr_ops, naggs, agg_what, agg_slot, P.cnt, P.sum,
                    P.mn, P.mx, P.mnp, P.mnl, P.mxp, P.mxl, &P.rows,
                    &P.amb, &P.cons, &P.qhit);
            };
            ScanPool::instance().run_parts(nt, [&](int pi) {
                runp(pi, pi == nt - 1 ? final_block : 0);
            });
            // a quote stops the merge at that part: later parts'
            // results describe rows past the stop point and are
            // discarded (the driver re-scans from *consumed via the
            // quote-aware array kernels)
            int nkeep = nt;
            for (int pi = 0; pi < nt; ++pi)
                if (parts[pi].qhit) {
                    nkeep = pi + 1;
                    break;
                }
            fused_merge(parts, cut, nkeep, naggs, agg_count, agg_sum,
                        agg_min, agg_max, agg_minpos, agg_minlen,
                        agg_maxpos, agg_maxlen, rows_out, amb_out);
            *consumed = cut[nkeep - 1] + parts[nkeep - 1].cons;
            *saw_quote = parts[nkeep - 1].qhit;
            return *rows_out;
        }
    }
    return csv_agg_fused_part(
        buf, len, delim, quote, final_block, col_idx, ncols, nleaves,
        lf_kind, lf_slot, lf_op, lf_fn, lf_fa, lf_fb, lf_num, lf_aoff,
        lf_alen, blob, likemask, prog, prog_len, expr_codes, expr_ops,
        naggs, agg_what, agg_slot, agg_count, agg_sum, agg_min, agg_max,
        agg_minpos, agg_minlen, agg_maxpos, agg_maxlen, rows_out,
        amb_out, consumed, saw_quote);
}

// ------------------------------------------------------ column emission

// Emit selected columns of masked rows as CSV records (projection
// path: SELECT a,b ... WHERE).  Caller guarantees the block is free of
// quote chars and \r (blocks containing either replay through the row
// engine's csv.writer), so cells copy verbatim: no quoting can ever be
// required — cells cannot contain the delimiter or newline by
// construction.  Missing cells (len -1, ragged rows) emit empty, the
// row engine's rendering of a None projection.  limit < 0 = unlimited.
// Returns rows emitted; *out_len = bytes written.
int64_t sel_emit_cols(const char *buf, const int32_t *starts,
                      const int32_t *lens, int64_t max_rows,
                      const int32_t *slots, int32_t nslots,
                      int64_t nrows, const uint8_t *mask, int64_t limit,
                      char delim, char *outbuf, int64_t *out_len) {
    int64_t n = 0, o = 0;
    for (int64_t r = 0; r < nrows; ++r) {
        if (mask && !mask[r])
            continue;
        if (limit >= 0 && n >= limit)
            break;
        for (int32_t c = 0; c < nslots; ++c) {
            if (c)
                outbuf[o++] = delim;
            int64_t idx = (int64_t)slots[c] * max_rows + r;
            int32_t l = lens[idx];
            if (l > 0) {
                memcpy(outbuf + o, buf + starts[idx], l);
                o += l;
            }
        }
        outbuf[o++] = '\n';
        ++n;
    }
    *out_len = o;
    return n;
}

// ---------------------------------------------- numeric expression leaves
// (run_prog and the per-cell evaluators live with the other cell
// helpers above so the fused kernels can share them.)

int64_t sel_cmp_expr(const char *buf, const int32_t *starts,
                     const int32_t *lens, int64_t n, int op,
                     double num_lit, const int32_t *codes,
                     const double *ops, int plen, uint8_t *mask) {
    int64_t amb = 0;
    const int opmask = OPMASK[op];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_cmp_expr(buf + starts[i], lens[i],
                                         opmask, num_lit, codes, ops,
                                         plen, &amb);
    return amb;
}

int64_t sel_json_cmp_expr(const char *buf, const int32_t *starts,
                          const int32_t *lens, const uint8_t *types,
                          int64_t n, int op, double num_lit,
                          const int32_t *codes, const double *ops,
                          int plen, uint8_t *mask) {
    int64_t amb = 0;
    const int opmask = OPMASK[op];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_json_cmp_expr(
            buf + starts[i], lens[i], types[i], opmask, num_lit, codes,
            ops, plen, &amb);
    return amb;
}

// ------------------------------------------------------------ NDJSON scan

// Per-line top-level key extraction.  For each needed key the scanner
// records the value extent and a type code:
//   0 missing, 1 null, 2 false, 3 true, 4 number, 5 string (no escapes,
//   extent = inner bytes), 6 ambiguous (string w/ escapes, nested
//   object/array, any parse doubt)
// A line that cannot be cleanly parsed sets every needed key on that
// row to 6 — the Python driver re-evaluates such rows exactly (and the
// row engine raises on truly invalid JSON, preserving error semantics).

static inline const char *skip_ws(const char *q, const char *le) {
    while (q < le && (*q == ' ' || *q == '\t' || *q == '\r'))
        ++q;
    return q;
}

// SWAR single-byte finder: cheaper than a memchr call for the short
// hops typical of compact JSON (keys and values of a few bytes).
// Returns le when absent.
__attribute__((always_inline))
static inline const char *find_byte(const char *p, const char *le,
                                    char c) {
    const uint64_t pat = 0x0101010101010101ULL * (unsigned char)c;
    while (p + 8 <= le) {
        uint64_t x;
        memcpy(&x, p, 8);
        uint64_t v = x ^ pat;
        uint64_t hit = (v - 0x0101010101010101ULL) & ~v &
                       0x8080808080808080ULL;
        if (hit)
            return p + (__builtin_ctzll(hit) >> 3);
        p += 8;
    }
    while (p < le && *p != c)
        ++p;
    return p;
}

// Strict JSON number grammar (json.loads' NUMBER_RE plus the NaN/
// Infinity/-Infinity constants Python's json accepts by default).
// parse_num accepts a DIFFERENT set (leading '+', '5.', '.5', '00',
// underscore-free Python-style) — a token parse_num likes but the
// grammar rejects is INVALID JSON and the row engine raises, so it
// must mark the line bad, never type 4.
static inline int json_num_grammar(const char *s, int32_t n) {
    if (n <= 0)
        return 0;
    int32_t i = 0;
    if (s[0] == 'N')
        return n == 3 && memcmp(s, "NaN", 3) == 0;
    if (s[0] == 'I')
        return n == 8 && memcmp(s, "Infinity", 8) == 0;
    if (s[0] == '-') {
        if (n == 9 && memcmp(s + 1, "Infinity", 8) == 0)
            return 1;
        i = 1;
    }
    if (i >= n)
        return 0;
    if (s[i] == '0') {
        ++i;
    } else if (s[i] >= '1' && s[i] <= '9') {
        while (i < n && (unsigned char)(s[i] - '0') <= 9)
            ++i;
    } else {
        return 0;
    }
    if (i < n && s[i] == '.') {
        ++i;
        if (i >= n || (unsigned char)(s[i] - '0') > 9)
            return 0;
        while (i < n && (unsigned char)(s[i] - '0') <= 9)
            ++i;
    }
    if (i < n && (s[i] == 'e' || s[i] == 'E')) {
        ++i;
        if (i < n && (s[i] == '+' || s[i] == '-'))
            ++i;
        if (i >= n || (unsigned char)(s[i] - '0') > 9)
            return 0;
        while (i < n && (unsigned char)(s[i] - '0') <= 9)
            ++i;
    }
    return i == n;
}

// Unified per-line machine (escape-capable: a backslash in a string
// VALUE only makes that one cell ambiguous instead of punting the
// whole line, so escape-heavy corpora keep the fast path).  Writes
// needed keys' extents/types at [k*stride + row]; returns 0 on a clean
// parse, 1 when the line is not a valid compact JSON object (escaped
// KEY text, structural garbage, invalid bare tokens) — the caller
// marks every key ambiguous and the Python replay decides (and raises
// exactly like the row engine on truly invalid lines).
static int json_parse_line(const char *buf, const char *ls, const char *le,
                           const char *const *keys, const int32_t *key_lens,
                           int32_t nkeys, int64_t stride, int64_t row,
                           int32_t *starts, int32_t *lens, uint8_t *types) {
    const char *q = ls;
    if (*q != '{')
        return 1;  // non-object line (array/scalar): row engine wraps
    q = skip_ws(q + 1, le);
    if (q < le && *q == '}')
        return skip_ws(q + 1, le) == le ? 0 : 1;
    for (;;) {
        if (q >= le || *q != '"')
            return 1;
        const char *ks = q + 1;
        const char *kq = ks;
        for (;;) {
            const char *h = find_byte(kq, le, '"');
            if (h == le)
                return 1;
            int bs = 0;
            const char *t = h - 1;
            while (t >= ks && *t == '\\') {
                ++bs;
                --t;
            }
            if (bs % 2) {
                return 1;  // escaped key text: let Python decide
            }
            kq = h;
            break;
        }
        // ANY backslash in the key means its raw bytes differ from the
        // decoded name (\uXXXX, \n, ...): a raw memcmp against the
        // queried column would silently diverge from the row engine —
        // same rule as the value side below
        if (memchr(ks, '\\', (size_t)(kq - ks)))
            return 1;
        int32_t klen = (int32_t)(kq - ks);
        q = skip_ws(kq + 1, le);
        if (q >= le || *q != ':')
            return 1;
        q = skip_ws(q + 1, le);
        if (q >= le)
            return 1;
        int ki = -1;
        for (int32_t k = 0; k < nkeys; ++k)
            if (key_lens[k] == klen &&
                (klen == 0 || (keys[k][0] == ks[0] &&
                               memcmp(keys[k], ks, klen) == 0))) {
                ki = k;
                break;
            }
        uint8_t vt;
        int32_t vs = (int32_t)(q - buf), vl;
        char v0 = *q;
        if (v0 == '"') {
            const char *ss = q + 1;
            const char *sq = ss;
            int sesc = 0;
            for (;;) {
                const char *h = find_byte(sq, le, '"');
                if (h == le)
                    return 1;
                int bs = 0;
                const char *t = h - 1;
                while (t >= ss && *t == '\\') {
                    ++bs;
                    --t;
                }
                if (bs % 2) {
                    sesc = 1;
                    sq = h + 1;
                    continue;
                }
                sq = h;
                break;
            }
            // ANY backslash in the value (not only one escaping the
            // closing quote) means the raw bytes differ from the
            // decoded string: \uXXXX, \n, \\ ... — Python decides
            // (comparing/matching raw `café` against a literal
            // would silently diverge from the row engine)
            if (!sesc && memchr(ss, '\\', (size_t)(sq - ss)))
                sesc = 1;
            vt = sesc ? 6 : 5;  // escaped value: Python semantics
            vs = (int32_t)(ss - buf);
            vl = (int32_t)(sq - ss);
            q = sq + 1;
        } else if (v0 == '{' || v0 == '[') {
            int d = 0, instr = 0;
            const char *z = q;
            while (z < le) {
                char c = *z;
                if (instr) {
                    if (c == '\\') {
                        z += 2;
                        continue;
                    }
                    if (c == '"')
                        instr = 0;
                } else if (c == '"') {
                    instr = 1;
                } else if (c == '{' || c == '[') {
                    ++d;
                } else if (c == '}' || c == ']') {
                    --d;
                    if (d == 0) {
                        ++z;
                        break;
                    }
                }
                ++z;
            }
            if (d != 0)
                return 1;
            vt = 6;  // nested value: Python semantics if needed
            vl = (int32_t)(z - q);
            q = z;
        } else if (v0 == 't') {
            if (le - q < 4 || memcmp(q, "true", 4) != 0)
                return 1;
            vt = 3;
            vl = 4;
            q += 4;
        } else if (v0 == 'f') {
            if (le - q < 5 || memcmp(q, "false", 5) != 0)
                return 1;
            vt = 2;
            vl = 5;
            q += 5;
        } else if (v0 == 'n') {
            if (le - q < 4 || memcmp(q, "null", 4) != 0)
                return 1;
            vt = 1;
            vl = 4;
            q += 4;
        } else {
            const char *z = q;
            while (z < le && *z != ',' && *z != '}' && *z != ' ' &&
                   *z != '\t' && *z != '\r')
                ++z;
            vl = (int32_t)(z - q);
            if (!json_num_grammar(q, vl))
                return 1;  // invalid bare token: row engine raises
            if (ki >= 0) {
                // needed value: exact double or ambiguous (>15-digit
                // ints, NaN/Infinity — json.loads parses those exactly
                // or as specials; Python decides)
                double dummy;
                vt = parse_num(q, vl, &dummy) ? 4 : 6;
            } else {
                vt = 4;  // never read: grammar validity is enough
            }
            q = z;
        }
        if (ki >= 0) {  // last occurrence wins (json.loads semantics)
            starts[(int64_t)ki * stride + row] = vs;
            lens[(int64_t)ki * stride + row] = vl;
            types[(int64_t)ki * stride + row] = vt;
        }
        q = skip_ws(q, le);
        if (q < le && *q == ',') {
            q = skip_ws(q + 1, le);
            continue;
        }
        if (q < le && *q == '}') {
            q = skip_ws(q + 1, le);
            return q == le ? 0 : 1;
        }
        return 1;
    }
}

// Returns rows scanned (complete lines; may stop early at max_rows with
// *consumed marking the resume point).  Blank lines are skipped (row
// engine skips them too).
int64_t sel_json_scan(const char *buf, int64_t len, int final_block,
                      const char *const *keys, const int32_t *key_lens,
                      int32_t nkeys, int64_t max_rows,
                      int32_t *starts, int32_t *lens, uint8_t *types,
                      int32_t *row_start, int32_t *row_len,
                      int64_t *consumed) {
    const char *p = buf, *end = buf + len;
    int64_t row = 0;
    *consumed = 0;
    while (p < end) {
        const char *nlp = find_byte(p, end, '\n');
        const char *nl = (nlp == end) ? nullptr : nlp;
        const char *line_end;
        if (nl == nullptr) {
            if (!final_block)
                break;  // incomplete trailing line
            line_end = end;
        } else {
            line_end = nl;
        }
        const char *ls = p, *le = line_end;
        while (ls < le && (*ls == ' ' || *ls == '\t' || *ls == '\r'))
            ++ls;
        while (le > ls && (le[-1] == ' ' || le[-1] == '\t' ||
                           le[-1] == '\r'))
            --le;
        if (ls == le) {  // blank line
            p = (nl ? nl + 1 : end);
            *consumed = p - buf;
            continue;
        }
        if (row >= max_rows)
            break;
        for (int32_t k = 0; k < nkeys; ++k)
            types[(int64_t)k * max_rows + row] = 0;  // missing (starts/
        // lens are only read for types >= 4, so no prefill needed)
        row_start[row] = (int32_t)(ls - buf);
        row_len[row] = (int32_t)(le - ls);
        if (json_parse_line(buf, ls, le, keys, key_lens, nkeys,
                            max_rows, row, starts, lens, types))
            for (int32_t k = 0; k < nkeys; ++k)
                types[(int64_t)k * max_rows + row] = 6;
        ++row;
        p = (nl ? nl + 1 : end);
        *consumed = p - buf;
    }
    row_start[row] = (int32_t)(*consumed);
    return row;
}

// Single-pass JSON number: strict JSON grammar (plus NaN/Infinity/
// -Infinity) fused with parse_num's exact-value computation — one walk
// where the array path pays three (token scan, grammar check, value
// parse).  Returns 0 invalid, 4 with *out holding exactly the double
// parse_num would produce, or 6 for valid-but-Python-decides tokens
// (>15 significant digits, NaN/Infinity, parse_num's length cap).
static inline int json_num_fwd(const char *s, const char *end,
                               const char **zp, double *out) {
    const char *p = s;
    int neg = 0;
    if (p < end && *p == 'N') {
        if (end - p >= 3 && memcmp(p, "NaN", 3) == 0) {
            *zp = p + 3;
            return 6;
        }
        return 0;
    }
    if (p < end && *p == 'I') {
        if (end - p >= 8 && memcmp(p, "Infinity", 8) == 0) {
            *zp = p + 8;
            return 6;
        }
        return 0;
    }
    if (p < end && *p == '-') {
        neg = 1;
        ++p;
        if (p < end && *p == 'I') {
            if (end - p >= 8 && memcmp(p, "Infinity", 8) == 0) {
                *zp = p + 8;
                return 6;
            }
            return 0;
        }
    }
    if (p >= end || (unsigned char)(*p - '0') > 9)
        return 0;
    uint64_t mant = 0;
    int digits = 0;
    if (*p == '0') {
        digits = 1;
        ++p;
        if (p < end && (unsigned char)(*p - '0') <= 9)
            return 0;  // JSON forbids leading zeros
    } else {
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            mant = mant * 10 + (unsigned char)(*p - '0');
            ++digits;
            ++p;
        }
    }
    int total = digits, exp10 = 0;
    if (p < end && *p == '.') {
        ++p;
        if (p >= end || (unsigned char)(*p - '0') > 9)
            return 0;  // JSON requires a digit after '.'
        const char *fs = p;
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            mant = mant * 10 + (unsigned char)(*p - '0');
            ++p;
        }
        int fd = (int)(p - fs);
        total += fd;
        exp10 -= fd;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
        ++p;
        int eneg = 0;
        if (p < end && (*p == '+' || *p == '-')) {
            eneg = (*p == '-');
            ++p;
        }
        if (p >= end || (unsigned char)(*p - '0') > 9)
            return 0;
        int ev = 0;
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            ev = ev * 10 + (*p - '0');
            if (ev > 400)
                ev = 400;
            ++p;
        }
        exp10 += eneg ? -ev : ev;
    }
    *zp = p;
    if (p - s >= 63 || total > 15)
        return 6;  // parse_num's caps: exact-int territory, replay
    double v;
    if (exp10 == 0) {
        v = (double)mant;
    } else if (exp10 > 0 && exp10 <= 22) {
        v = (double)mant * POW10[exp10];
    } else if (exp10 < 0 && exp10 >= -22) {
        v = (double)mant / POW10[-exp10];
    } else {
        char tmp[64];
        int n = (int)(p - s);
        memcpy(tmp, s, n);
        tmp[n] = 0;
        char *ep = nullptr;
        v = strtod(tmp, &ep);
        if (ep != tmp + n)
            return 6;
        *out = v;  // strtod consumed the sign itself
        return 4;
    }
    *out = neg ? -v : v;
    return 4;
}

// Forward line parser for the fused JSON path: ONE walk that finds the
// line end itself (no newline pre-scan), validates, extracts needed
// keys, and caches exact numeric values in vnum[].  Returns 0 ok
// (*next = just past the newline / end), 1 bad line (caller resyncs to
// the next newline and replays), 2 incomplete (hit the block end
// before the line ended and this is not the final block — the bytes
// become the next block's tail).  A raw '\n' always ends the line: it
// cannot legally appear inside a single-line JSON document, matching
// how the row engine splits the stream.
static int json_line_fwd(const char *buf, const char *ls, const char *end,
                         int final_block, const char *const *keys,
                         const int32_t *key_lens, int32_t nkeys,
                         int32_t *vpos, int32_t *vlen, uint8_t *vtype,
                         double *vnum, const char **next) {
    const char *q = ls;
    if (*q != '{')
        return 1;  // non-object line (array/scalar): row engine wraps
    ++q;
    int first = 1;
    for (;;) {
        while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
            ++q;
        if (q >= end)
            return final_block ? 1 : 2;
        if (first && *q == '}') {  // {} only: {"a":1,} is invalid JSON
            ++q;
            while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
                ++q;
            if (q >= end) {
                *next = end;
                return final_block ? 0 : 2;
            }
            if (*q == '\n') {
                *next = q + 1;
                return 0;
            }
            return 1;
        }
        first = 0;
        if (*q != '"')
            return 1;
        const char *ks = q + 1;
        const char *kq = ks;
        for (;;) {
            const char *h = scan2(kq, end, '"', '\n');
            if (h == end)
                return final_block ? 1 : 2;
            if (*h == '\n')
                return 1;  // unterminated key on this line
            int bs = 0;
            const char *t = h - 1;
            while (t >= ks && *t == '\\') {
                ++bs;
                --t;
            }
            if (bs % 2)
                return 1;  // escaped key text: let Python decide
            kq = h;
            break;
        }
        // any backslash in the key => raw bytes != decoded name:
        // replay (same rule as json_parse_line above)
        if (memchr(ks, '\\', (size_t)(kq - ks)))
            return 1;
        int32_t klen = (int32_t)(kq - ks);
        q = kq + 1;
        while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
            ++q;
        if (q >= end)
            return final_block ? 1 : 2;
        if (*q != ':')
            return 1;
        ++q;
        while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
            ++q;
        if (q >= end)
            return final_block ? 1 : 2;
        int ki = -1;
        for (int32_t k = 0; k < nkeys; ++k)
            if (key_lens[k] == klen &&
                (klen == 0 || (keys[k][0] == ks[0] &&
                               memcmp(keys[k], ks, klen) == 0))) {
                ki = k;
                break;
            }
        uint8_t vt;
        int32_t vs = (int32_t)(q - buf), vl;
        double vv = 0.0;
        char v0 = *q;
        if (v0 == '"') {
            const char *ss = q + 1;
            const char *sq = ss;
            int sesc = 0;
            for (;;) {
                const char *h = scan2(sq, end, '"', '\n');
                if (h == end)
                    return final_block ? 1 : 2;
                if (*h == '\n')
                    return 1;  // raw newline in string: invalid JSON
                int bs = 0;
                const char *t = h - 1;
                while (t >= ss && *t == '\\') {
                    ++bs;
                    --t;
                }
                if (bs % 2) {
                    sesc = 1;
                    sq = h + 1;
                    continue;
                }
                sq = h;
                break;
            }
            // any backslash => raw bytes != decoded string: replay
            // (same rule as json_parse_line above)
            if (!sesc && memchr(ss, '\\', (size_t)(sq - ss)))
                sesc = 1;
            vt = sesc ? 6 : 5;  // escaped value: Python semantics
            vs = (int32_t)(ss - buf);
            vl = (int32_t)(sq - ss);
            q = sq + 1;
        } else if (v0 == '{' || v0 == '[') {
            int d = 0, instr = 0;
            const char *z = q;
            while (z < end) {
                char c = *z;
                if (c == '\n')
                    return 1;  // line ends inside the nested value
                if (instr) {
                    if (c == '\\') {
                        z += 2;
                        continue;
                    }
                    if (c == '"')
                        instr = 0;
                } else if (c == '"') {
                    instr = 1;
                } else if (c == '{' || c == '[') {
                    ++d;
                } else if (c == '}' || c == ']') {
                    --d;
                    if (d == 0) {
                        ++z;
                        break;
                    }
                }
                ++z;
            }
            if (d != 0)
                return final_block ? 1 : 2;
            vt = 6;  // nested value: Python semantics if needed
            vl = (int32_t)(z - q);
            q = z;
        } else if (v0 == 't') {
            if (end - q < 4 || memcmp(q, "true", 4) != 0)
                return (end - q < 4 && !final_block) ? 2 : 1;
            vt = 3;
            vl = 4;
            q += 4;
        } else if (v0 == 'f') {
            if (end - q < 5 || memcmp(q, "false", 5) != 0)
                return (end - q < 5 && !final_block) ? 2 : 1;
            vt = 2;
            vl = 5;
            q += 5;
        } else if (v0 == 'n') {
            if (end - q < 4 || memcmp(q, "null", 4) != 0)
                return (end - q < 4 && !final_block) ? 2 : 1;
            vt = 1;
            vl = 4;
            q += 4;
        } else {
            const char *z;
            int r = json_num_fwd(q, end, &z, &vv);
            if (r == 0)
                return 1;
            if (z == end && !final_block)
                return 2;  // the number may continue in the next block
            vt = (uint8_t)r;
            vl = (int32_t)(z - q);
            q = z;
        }
        if (ki >= 0) {  // last occurrence wins (json.loads semantics)
            vpos[ki] = vs;
            vlen[ki] = vl;
            vtype[ki] = vt;
            vnum[ki] = vv;
        }
        while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
            ++q;
        if (q >= end)
            return final_block ? 1 : 2;
        if (*q == ',') {
            ++q;
            continue;
        }
        if (*q == '}') {
            ++q;
            while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
                ++q;
            if (q >= end) {
                *next = end;
                return final_block ? 0 : 2;
            }
            if (*q == '\n') {
                *next = q + 1;
                return 0;
            }
            return 1;
        }
        return 1;
    }
}

// Fused one-pass NDJSON aggregate scan: per-line parse + WHERE program
// + aggregate fold without materializing per-key index arrays.  Same
// leaf/program encoding as sel_csv_agg_fused, with the JSON leaf
// evaluators (kind 0 cmp takes lf_isnum instead of splitting num/str).
// A structurally bad line bumps *amb_out (the whole consumed span
// replays so the row engine can raise in record order).
static int64_t json_agg_fused_part(
    const char *buf, int64_t len, int final_block,
    const char *const *keys, const int32_t *key_lens, int32_t nkeys,
    int32_t nleaves, const int32_t *lf_kind, const int32_t *lf_slot,
    const int32_t *lf_op, const int32_t *lf_isnum, const int32_t *lf_fn,
    const int32_t *lf_fa, const int32_t *lf_fb, const double *lf_num,
    const int32_t *lf_aoff, const int32_t *lf_alen, const char *blob,
    const unsigned char *likemask, const int32_t *prog, int32_t prog_len,
    const int32_t *expr_codes, const double *expr_ops,
    int32_t naggs, const int32_t *agg_what, const int32_t *agg_slot,
    int64_t *agg_count, double *agg_sum, double *agg_min, double *agg_max,
    int32_t *agg_minpos, int32_t *agg_minlen,
    int32_t *agg_maxpos, int32_t *agg_maxlen,
    int64_t *rows_out, int64_t *amb_out, int64_t *consumed) {
    int32_t vpos[FUSED_MAX_COLS], vlen[FUSED_MAX_COLS];
    uint8_t vtype[FUSED_MAX_COLS];
    double vnum[FUSED_MAX_COLS];
    char scratch[FN_SCRATCH];
    int64_t row = 0, amb = 0;
    for (int32_t a = 0; a < naggs; ++a) {
        agg_count[a] = 0;
        agg_sum[a] = 0.0;
        agg_min[a] = agg_max[a] = 0.0;
        agg_minpos[a] = agg_maxpos[a] = 0;
        agg_minlen[a] = agg_maxlen[a] = -1;
    }
    // common-shape specialization (COUNT(*) with at most one numeric
    // comparison leaf): per-line work collapses to a cached-value
    // compare + increment, no program interpreter
    const int count_star_only = (naggs == 1 && agg_slot[0] < 0);
    const int simple_cmp =
        nleaves == 1 && prog_len == 1 && lf_kind[0] == 0 &&
        lf_fn[0] == 0 && lf_isnum[0] == 1;
    const int s_opmask = simple_cmp ? OPMASK[lf_op[0]] : 0;
    const double s_num = simple_cmp ? lf_num[0] : 0.0;
    const int32_t s_slot = simple_cmp ? lf_slot[0] : 0;

    auto eval_line_slow = [&]() __attribute__((noinline)) {
        int ok = 1;
        if (nleaves) {
            uint8_t st[FUSED_MAX_STACK];
            int sp = 0;
            for (int32_t pi = 0; pi < prog_len; ++pi) {
                int32_t e = prog[pi];
                if (e >= 0) {
                    const int32_t sl = lf_slot[e];
                    const char *s = buf + vpos[sl];
                    const int32_t l = vlen[sl];
                    const uint8_t t = vtype[sl];
                    int r;
                    switch (lf_kind[e]) {
                    case 0:
                        if (t == 4 && lf_isnum[e] &&
                            lf_fn[e] == FN_NONE) {
                            // exact value cached by the line parser
                            const double v = vnum[sl];
                            const int c = (v > lf_num[e]) -
                                          (v < lf_num[e]);
                            r = (OPMASK[lf_op[e]] >> (c + 1)) & 1;
                            break;
                        }
                        r = cell_json_cmp(
                            s, l, t, lf_op[e], OPMASK[lf_op[e]],
                            lf_num[e], lf_isnum[e], blob + lf_aoff[e],
                            lf_alen[e], lf_fn[e], lf_fa[e], lf_fb[e],
                            scratch, &amb);
                        break;
                    case 2:
                        r = cell_json_like(
                            s, l, t, blob + lf_aoff[e], lf_alen[e],
                            likemask + lf_aoff[e], lf_fn[e], lf_fa[e],
                            lf_fb[e], scratch, &amb);
                        break;
                    case 3:
                        r = cell_json_isnull(l, t, &amb);
                        break;
                    case 4:
                        r = (t != 0 && t != 1);
                        break;
                    default:  // 5: expr program
                        r = cell_json_cmp_expr(
                            s, l, t, OPMASK[lf_op[e]], lf_num[e],
                            expr_codes + lf_aoff[e],
                            expr_ops + lf_aoff[e], lf_alen[e], &amb);
                    }
                    st[sp++] = (uint8_t)r;
                } else if (e == -1) {
                    st[sp - 2] &= st[sp - 1];
                    --sp;
                } else if (e == -2) {
                    st[sp - 2] |= st[sp - 1];
                    --sp;
                } else {
                    st[sp - 1] ^= 1;
                }
            }
            ok = st[0];
        }
        if (!ok || amb)
            return;
        for (int32_t a = 0; a < naggs; ++a) {
            const int32_t sl = agg_slot[a];
            if (sl < 0) {  // COUNT(*)
                ++agg_count[a];
                continue;
            }
            const uint8_t t = vtype[sl];
            const int32_t l = vlen[sl];
            if (t == 0 || t == 1)
                continue;  // missing/null
            if (t == 5 && l == 0)
                continue;  // "" skipped like CSV empty
            if (t == 6 || t == 2 || t == 3) {
                ++amb;
                continue;
            }
            if (agg_what[a] == 0) {
                ++agg_count[a];
                continue;
            }
            double v;
            if (t == 4) {
                v = vnum[sl];
            } else if (!parse_num(buf + vpos[sl], l, &v)) {
                ++amb;
                continue;
            }
            ++agg_count[a];
            if (agg_what[a] == 1) {
                agg_sum[a] += v;
            } else {
                if (agg_minlen[a] < 0 || v < agg_min[a]) {
                    agg_min[a] = v;
                    agg_minpos[a] = vpos[sl];
                    agg_minlen[a] = l;
                }
                if (agg_maxlen[a] < 0 || v > agg_max[a]) {
                    agg_max[a] = v;
                    agg_maxpos[a] = vpos[sl];
                    agg_maxlen[a] = l;
                }
            }
        }
    };

    const char *p = buf, *end = buf + len;
    int64_t cons = 0;  // local: a per-line store through the out
    // pointer would be an aliasing barrier in this loop
    while (p < end) {
        const char *q = p;
        while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
            ++q;
        if (q >= end) {
            if (final_block)
                cons = len;  // trailing whitespace only
            break;
        }
        if (*q == '\n') {  // blank line: skipped like the row engine
            p = q + 1;
            cons = p - buf;
            continue;
        }
        int st;
        const char *nx = end;
        if (!amb) {  // once ambiguous the span replays: resync only
            for (int32_t k = 0; k < nkeys; ++k)
                vtype[k] = 0;
            st = json_line_fwd(buf, q, end, final_block, keys, key_lens,
                               nkeys, vpos, vlen, vtype, vnum, &nx);
        } else {
            st = 1;
        }
        if (st == 2)
            break;  // incomplete trailing line: next block's tail
        if (st == 1) {
            // bad (or post-ambiguity resync): the line replays — but
            // only once it is COMPLETE in this block
            const char *nl = find_byte(q, end, '\n');
            if (nl == end) {
                if (!final_block)
                    break;  // reparse whole line next block
                nx = end;
            } else {
                nx = nl + 1;
            }
            ++amb;
        } else {
            if (count_star_only && nleaves == 0)
                ++agg_count[0];
            else if (count_star_only && simple_cmp) {
                const uint8_t t = vtype[s_slot];
                if (t == 4) {
                    const double v = vnum[s_slot];
                    const int c = (v > s_num) - (v < s_num);
                    agg_count[0] += (s_opmask >> (c + 1)) & 1;
                } else {
                    agg_count[0] += cell_json_cmp(
                        buf + vpos[s_slot], vlen[s_slot], t, lf_op[0],
                        s_opmask, s_num, 1, blob + lf_aoff[0],
                        lf_alen[0], 0, 0, 0, scratch, &amb) && !amb;
                }
            } else {
                eval_line_slow();
            }
        }
        ++row;
        p = nx;
        cons = p - buf;
    }
    *consumed = cons;
    *rows_out = row;
    *amb_out = amb;
    return row;
}

int64_t sel_json_agg_fused(
    const char *buf, int64_t len, int final_block,
    const char *const *keys, const int32_t *key_lens, int32_t nkeys,
    int32_t nleaves, const int32_t *lf_kind, const int32_t *lf_slot,
    const int32_t *lf_op, const int32_t *lf_isnum, const int32_t *lf_fn,
    const int32_t *lf_fa, const int32_t *lf_fb, const double *lf_num,
    const int32_t *lf_aoff, const int32_t *lf_alen, const char *blob,
    const unsigned char *likemask, const int32_t *prog, int32_t prog_len,
    const int32_t *expr_codes, const double *expr_ops,
    int32_t naggs, const int32_t *agg_what, const int32_t *agg_slot,
    int64_t *agg_count, double *agg_sum, double *agg_min, double *agg_max,
    int32_t *agg_minpos, int32_t *agg_minlen,
    int32_t *agg_maxpos, int32_t *agg_maxlen,
    int64_t *rows_out, int64_t *amb_out, int64_t *consumed) {
    const int T = fused_threads();
    if (T > 1 && len >= (1 << 20) && naggs <= FUSED_MAX_AGGS) {
        int64_t cut[FUSED_MAX_THREADS + 1];
        const int nt = fused_cuts(buf, len, T, cut);
        if (nt > 1) {
            FusedPart parts[FUSED_MAX_THREADS];
            auto runp = [&](int pi, int fin) {
                FusedPart &P = parts[pi];
                json_agg_fused_part(
                    buf + cut[pi], cut[pi + 1] - cut[pi], fin, keys,
                    key_lens, nkeys, nleaves, lf_kind, lf_slot, lf_op,
                    lf_isnum, lf_fn, lf_fa, lf_fb, lf_num, lf_aoff,
                    lf_alen, blob, likemask, prog, prog_len, expr_codes,
                    expr_ops, naggs, agg_what, agg_slot, P.cnt, P.sum,
                    P.mn, P.mx, P.mnp, P.mnl, P.mxp, P.mxl, &P.rows,
                    &P.amb, &P.cons);
            };
            ScanPool::instance().run_parts(nt, [&](int pi) {
                runp(pi, pi == nt - 1 ? final_block : 0);
            });
            fused_merge(parts, cut, nt, naggs, agg_count, agg_sum,
                        agg_min, agg_max, agg_minpos, agg_minlen,
                        agg_maxpos, agg_maxlen, rows_out, amb_out);
            *consumed = cut[nt - 1] + parts[nt - 1].cons;
            return *rows_out;
        }
    }
    return json_agg_fused_part(
        buf, len, final_block, keys, key_lens, nkeys, nleaves, lf_kind,
        lf_slot, lf_op, lf_isnum, lf_fn, lf_fa, lf_fb, lf_num, lf_aoff,
        lf_alen, blob, likemask, prog, prog_len, expr_codes, expr_ops,
        naggs, agg_what, agg_slot, agg_count, agg_sum, agg_min, agg_max,
        agg_minpos, agg_minlen, agg_maxpos, agg_maxlen, rows_out,
        amb_out, consumed);
}

// JSON numeric-literal comparison: number cells (type 4) and
// numeric-looking string cells (type 5) compare numerically; string
// cells that don't parse compare textually; bool/null/ambiguous per
// row-engine rules.  Text compare of a NUMBER cell is ambiguous
// (Python renders str(5.00) as "5.0" — raw bytes may differ).
int64_t sel_json_cmp(const char *buf, const int32_t *starts,
                     const int32_t *lens, const uint8_t *types,
                     int64_t n, int op, double num_lit, int lit_is_num,
                     const char *str_lit, int32_t str_len,
                     uint8_t *mask, int fn, int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    const int opmask = OPMASK[op];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_json_cmp(
            buf + starts[i], lens[i], types[i], op, opmask, num_lit,
            lit_is_num, str_lit, str_len, fn, fn_a, fn_b, scratch, &amb);
    return amb;
}

// JSON LIKE: string cells only (row engine str()s other types —
// ambiguous).  Missing/null => false.
int64_t sel_json_like(const char *buf, const int32_t *starts,
                      const int32_t *lens, const uint8_t *types,
                      int64_t n, const char *pat, int32_t pat_len,
                      const unsigned char *lit, uint8_t *mask, int fn,
                 int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_json_like(
            buf + starts[i], lens[i], types[i], pat, pat_len, lit, fn,
            fn_a, fn_b, scratch, &amb);
    return amb;
}

// JSON validity (for NOT/negate composition): value present and not null.
void sel_json_valid(const uint8_t *types, int64_t n, uint8_t *mask) {
    for (int64_t i = 0; i < n; ++i)
        mask[i] = types[i] != 0 && types[i] != 1;
}

// JSON IS NULL: missing key or null value, or an empty string (row
// engine: v is None or v == "").  Type-6 cells (ambiguous value OR a
// structurally bad line) are counted in the return value so the
// driver replays them — a bad NDJSON line must raise like the row
// engine even when the WHERE is IS NULL-only.
int64_t sel_json_isnull(const int32_t *lens, const uint8_t *types,
                        int64_t n, uint8_t *mask) {
    int64_t amb = 0;
    for (int64_t i = 0; i < n; ++i)
        mask[i] = (uint8_t)cell_json_isnull(lens[i], types[i], &amb);
    return amb;
}

// JSON aggregate fold (same contract as sel_agg).  Number cells and
// numeric strings fold; bool/nested/escaped => ambiguous; null/missing
// and empty strings skip.
int64_t sel_json_agg(const char *buf, const int32_t *starts,
                     const int32_t *lens, const uint8_t *types,
                     int64_t n, const uint8_t *mask, int what,
                     double *sum, double *minv, double *maxv,
                     int64_t *argmin, int64_t *argmax, int64_t *amb) {
    int64_t cnt = 0;
    *amb = 0;
    double s = 0.0, lo = 0.0, hi = 0.0;
    int64_t ilo = -1, ihi = -1;
    for (int64_t i = 0; i < n; ++i) {
        if (mask && !mask[i])
            continue;
        uint8_t t = types[i];
        if (t == 0 || t == 1)
            continue;  // missing/null
        if (t == 5 && lens[i] == 0)
            continue;  // "" skipped like CSV empty
        if (t == 6 || t == 2 || t == 3) {
            ++*amb;
            continue;
        }
        if (what == 0) {
            ++cnt;
            continue;
        }
        double v;
        if (!parse_num(buf + starts[i], lens[i], &v)) {
            ++*amb;
            continue;
        }
        ++cnt;
        if (what == 1) {
            s += v;
        } else {
            if (ilo < 0 || v < lo) {
                lo = v;
                ilo = i;
            }
            if (ihi < 0 || v > hi) {
                hi = v;
                ihi = i;
            }
        }
    }
    *sum = s;
    *minv = lo;
    *maxv = hi;
    *argmin = ilo;
    *argmax = ihi;
    return cnt;
}

}  // extern "C"
