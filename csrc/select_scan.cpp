// Native S3 Select scan kernels: CSV structural scan + predicate masks +
// aggregates, and an NDJSON top-level-key scanner.
//
// This is the TPU-framework analogue of the reference's SIMD Select
// accelerators (internal/s3select/simdj/reader.go simdjson path and the
// generated-assembly CSV scanner behind select_benchmark_test.go): the
// hot loop — tokenize, extract needed fields, evaluate simple predicates,
// fold aggregates — runs in C++ at memory speed, while the Python driver
// (minio_tpu/select/native.py) keeps row-engine semantics by re-evaluating
// any block whose cells are AMBIGUOUS (values Python would coerce
// differently than the strict C parsers below: whitespace-padded numbers,
// "inf"/"nan", underscore digits, >2^53 ints, escaped quotes, JSON string
// escapes, non-canonical number text...).  Ambiguity is a per-call flag:
// correctness never depends on the fast path guessing.
//
// Layout contracts (all little-endian host):
//   starts/lens: int32 arrays of shape [ncols_needed][max_rows] (row-major
//   per column).  lens[r] == -1 => column missing in that row (null);
//   lens[r] == -2 => cell needs Python unquoting (contains doubled quote).
//   Otherwise [start, start+len) are the cell's logical bytes in buf
//   (surrounding CSV quotes stripped; trailing \r before \n stripped).
//
// Exposed via ctypes (see minio_tpu/select/native.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <cstdlib>

#if defined(__SSE2__)
#include <emmintrin.h>
#endif
#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// ------------------------------------------------------------------ utils

// Find next byte equal to a or b in [p, end); returns end if none.
static inline const char *scan2(const char *p, const char *end,
                                char a, char b) {
#if defined(__SSE2__)
    const __m128i va = _mm_set1_epi8(a);
    const __m128i vb = _mm_set1_epi8(b);
    while (p + 16 <= end) {
        __m128i x = _mm_loadu_si128(reinterpret_cast<const __m128i *>(p));
        int m = _mm_movemask_epi8(
            _mm_or_si128(_mm_cmpeq_epi8(x, va), _mm_cmpeq_epi8(x, vb)));
        if (m)
            return p + __builtin_ctz(m);
        p += 16;
    }
#endif
    while (p < end && *p != a && *p != b)
        ++p;
    return p;
}

// Strict numeric parse matching the canonical subset of Python
// int()/float(): [+-]? (D+ | D+.D* | .D+) ([eE][+-]?D+)?
// Returns 1 and *out on success; 0 otherwise.  Cells with more than 15
// significant digits report failure (the caller treats them as
// ambiguous — Python compares big ints exactly, double cannot).
//
// Fast path: mantissa accumulated as uint64 (exact for <= 15 digits)
// scaled by an exact power of ten — one rounding, identical to strtod
// in this range (the classic Gay fast path).  Exponents outside |22|
// fall back to strtod for correct rounding.
static const double POW10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10,
    1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21,
    1e22};

// SWAR 8-digit block evaluator (Lemire): `raw` holds eight ASCII digits
// in memory order (first digit in the lowest byte).
static inline int all_digits8(uint64_t v) {
    return (((v & 0xF0F0F0F0F0F0F0F0ULL) |
             (((v + 0x0606060606060606ULL) & 0xF0F0F0F0F0F0F0F0ULL) >>
              4)) == 0x3333333333333333ULL);
}

static inline uint32_t eval8(uint64_t val) {
    const uint64_t mask = 0x000000FF000000FFULL;
    const uint64_t mul1 = 0x000F424000000064ULL;  // 100 + (1000000 << 32)
    const uint64_t mul2 = 0x0000271000000001ULL;  // 1 + (10000 << 32)
    val -= 0x3030303030303030ULL;
    val = (val * 10) + (val >> 8);
    val = (((val & mask) * mul1) + (((val >> 16) & mask) * mul2)) >> 32;
    return (uint32_t)val;
}

// op truth table over the 3-way compare c in {-1,0,1}: bit (c+1) of
// OPMASK[op].  ops: 0 '=', 1 '!=', 2 '<', 3 '<=', 4 '>', 5 '>='
static const int OPMASK[6] = {2, 5, 1, 3, 4, 6};

// Fast path for pure-integer cells of <= 8 digits.  REQUIRES 8 readable
// bytes at s (the Python driver pads every block with 8 slack bytes).
__attribute__((always_inline))
static inline int parse_int8_swar(const char *s, int32_t n, double *out) {
    uint64_t raw;
    memcpy(&raw, s, 8);
    if (n < 8)
        raw = (raw << ((8 - n) * 8)) |
              (0x3030303030303030ULL >> (n * 8));
    if (!all_digits8(raw))
        return 0;
    *out = (double)eval8(raw);
    return 1;
}

static inline int parse_num(const char *s, int32_t n, double *out) {
    if (n <= 0 || n >= 63)
        return 0;
    if (n <= 8 && parse_int8_swar(s, n, out))
        return 1;
    const char *p = s, *end = s + n;
    int neg = 0;
    if (*p == '+' || *p == '-') {
        neg = (*p == '-');
        ++p;
    }
    uint64_t mant = 0;
    int digits = 0;
    while (p < end && (unsigned char)(*p - '0') <= 9) {
        mant = mant * 10 + (unsigned char)(*p - '0');
        ++digits;
        ++p;
    }
    int total = digits;
    int exp10 = 0;
    if (p < end && *p == '.') {
        ++p;
        const char *fs = p;
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            mant = mant * 10 + (unsigned char)(*p - '0');
            ++p;
        }
        int fd = (int)(p - fs);
        total += fd;
        exp10 -= fd;
    }
    if (total == 0)
        return 0;
    if (total > 15)
        return 0;  // exact-int / long-mantissa territory: Python decides
    if (p < end && (*p == 'e' || *p == 'E')) {
        ++p;
        int eneg = 0;
        if (p < end && (*p == '+' || *p == '-')) {
            eneg = (*p == '-');
            ++p;
        }
        int ed = 0, ev = 0;
        while (p < end && (unsigned char)(*p - '0') <= 9) {
            ev = ev * 10 + (*p - '0');
            if (ev > 400)
                ev = 400;
            ++ed;
            ++p;
        }
        if (!ed)
            return 0;
        exp10 += eneg ? -ev : ev;
    }
    if (p != end)
        return 0;
    double v;
    if (exp10 == 0) {
        v = (double)mant;
    } else if (exp10 > 0 && exp10 <= 22) {
        v = (double)mant * POW10[exp10];
    } else if (exp10 < 0 && exp10 >= -22) {
        v = (double)mant / POW10[-exp10];
    } else {
        // rare huge/tiny exponent: strtod for correct rounding
        char tmp[64];
        memcpy(tmp, s, n);
        tmp[n] = 0;
        char *ep = nullptr;
        v = strtod(tmp, &ep);
        if (ep != tmp + n)
            return 0;
        *out = v;  // strtod consumed the sign itself
        return 1;
    }
    *out = neg ? -v : v;
    return 1;
}

// Would Python's int()/float() possibly accept (or differently coerce)
// this cell even though parse_num rejected it?  Conservative: any cell
// starting with whitespace/sign/digit/dot/underscore/'i'/'n' (inf/nan)
// or a non-ASCII byte (unicode digits/whitespace), or ending with
// whitespace, is AMBIGUOUS and forces the block onto the Python path.
static int num_ambiguous(const char *s, int32_t n) {
    if (n <= 0)
        return 0;  // empty: Python rejects too => clean text
    unsigned char c0 = (unsigned char)s[0];
    unsigned char cl = (unsigned char)s[n - 1];
    if (c0 >= 0x80 || cl >= 0x80)
        return 1;
    if (c0 == ' ' || c0 == '\t' || cl == ' ' || cl == '\t')
        return 1;
    if (c0 == '+' || c0 == '-' || c0 == '.' || c0 == '_')
        return 1;
    if (c0 >= '0' && c0 <= '9')
        return 1;
    if (c0 == 'i' || c0 == 'I' || c0 == 'n' || c0 == 'N')
        return 1;
    return 0;
}

// UTF-8 aware LIKE matcher ('%' = any run, '_' = one codepoint).
// Pattern arrives pre-processed by Python: escape characters resolved
// into a literal-mask byte array (1 = literal byte, 0 = wildcard role).
static int utf8_next(const char *s, int i, int n) {
    ++i;
    while (i < n && ((unsigned char)s[i] & 0xC0) == 0x80)
        ++i;
    return i;
}

static int like_match(const char *s, int sn, const char *pat, int pn,
                      const unsigned char *lit) {
    // iterative glob with single-% backtracking (classic algorithm)
    int si = 0, pi = 0, star_p = -1, star_s = -1;
    while (si < sn) {
        if (pi < pn && !lit[pi] && pat[pi] == '%') {
            star_p = ++pi;
            star_s = si;
            continue;
        }
        if (pi < pn && !lit[pi] && pat[pi] == '_') {
            si = utf8_next(s, si, sn);
            ++pi;
            continue;
        }
        if (pi < pn && pat[pi] == s[si] &&
            (lit[pi] || (pat[pi] != '%' && pat[pi] != '_'))) {
            ++si;
            ++pi;
            continue;
        }
        if (star_p >= 0) {
            star_s = utf8_next(s, star_s, sn);
            si = star_s;
            pi = star_p;
            continue;
        }
        return 0;
    }
    while (pi < pn && !lit[pi] && pat[pi] == '%')
        ++pi;
    return pi == pn;
}

// -------------------------------------------------------------- CSV scan

// Quote-free fast scan: one linear SIMD pass extracting separator
// positions, constant work per separator.  Preconditions (checked by
// the caller): no quote byte anywhere in [buf, len).
static int64_t csv_scan_fast(const char *buf, int64_t len, char delim,
                             int final_block, const int32_t *col_idx,
                             int32_t ncols, int64_t max_rows,
                             int32_t *starts, int32_t *lens,
                             int32_t *row_start, int64_t *consumed) {
    int64_t row = 0;
    int32_t field = 0, k = 0;
    int64_t field_start = 0, row_begin = 0;
    int overflow = 0;
    const int32_t col0 = col_idx[0];
    const int single = (ncols == 1);
    for (int32_t c = 0; c < ncols; ++c)
        lens[(int64_t)c * max_rows] = -1;

    // handle() -> 0 normal, 1 stop (max_rows), 2 all needed cells of
    // this row captured (caller may skip remaining delimiters until the
    // next newline — a large win for wide rows)
    auto handle = [&](int64_t pos, int is_nl)
        __attribute__((always_inline)) {
        int captured = 0;
        if (single ? (field == col0)
                   : (k < ncols && col_idx[k] == field)) {
            int64_t ce = pos;
            if (is_nl && ce > field_start && buf[ce - 1] == '\r')
                --ce;
            starts[(int64_t)k * max_rows + row] = (int32_t)field_start;
            lens[(int64_t)k * max_rows + row] = (int32_t)(ce - field_start);
            ++k;
            captured = (k == ncols);
        }
        field_start = pos + 1;
        if (is_nl) {
            int64_t rl = pos - row_begin;
            if (rl == 0 || (rl == 1 && buf[row_begin] == '\r')) {
                // blank record: csv.reader (the row engine) skips it
                for (int32_t cc = 0; cc < k; ++cc)
                    lens[(int64_t)cc * max_rows + row] = -1;
                row_begin = pos + 1;
                field = 0;
                k = 0;
                return 0;
            }
            if (row_start)
                row_start[row] = (int32_t)row_begin;
            ++row;
            row_begin = pos + 1;  // consumed covers every counted row
            if (row >= max_rows) {
                overflow = 1;
                return 1;
            }
            for (int32_t cc = 0; cc < ncols; ++cc)
                lens[(int64_t)cc * max_rows + row] = -1;
            field = 0;
            k = 0;
            return 0;
        }
        ++field;
        return captured ? 2 : 0;
    };

    int64_t i = 0;
#if defined(__AVX2__)
    const __m256i vd = _mm256_set1_epi8(delim);
    const __m256i vn = _mm256_set1_epi8('\n');
    int skipping = 0;  // row's needed cells done: only newlines matter
    while (i + 32 <= len && !overflow) {
        __m256i x = _mm256_loadu_si256(
            reinterpret_cast<const __m256i *>(buf + i));
        uint32_t mn = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(x, vn));
        if (skipping && mn == 0) {
            i += 32;  // whole chunk is mid-row noise
            continue;
        }
        uint32_t m = (uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(x, vd)) | mn;
        if (skipping) {
            m &= ~(((uint32_t)1 << __builtin_ctz(mn)) - 1);
            skipping = 0;
        }
        while (m) {
            int b = __builtin_ctz(m);
            m &= m - 1;
            int rc = handle(i + b, (mn >> b) & 1);
            if (rc == 1)
                break;
            if (rc == 2) {
                // drop delimiter bits until the next newline
                uint32_t nn = mn & m;
                if (nn) {
                    m &= ~(((uint32_t)1 << __builtin_ctz(nn)) - 1);
                } else {
                    m = 0;
                    skipping = 1;
                }
                // field counting is moot while skipping: fields between
                // here and the newline are never needed (k == ncols)
            }
        }
        i += 32;
    }
    if (skipping) {
        // resume the scalar tail at the next newline
        const char *nlp = static_cast<const char *>(
            memchr(buf + i, '\n', len - i));
        i = nlp ? (nlp - buf) : len;
    }
#endif
    while (i < len && !overflow) {
        char c = buf[i];
        if (c == delim || c == '\n') {
            int rc = handle(i, c == '\n');
            if (rc == 1)
                break;
            if (rc == 2) {
                const char *nlp = static_cast<const char *>(
                    memchr(buf + i + 1, '\n', len - i - 1));
                if (nlp == nullptr) {
                    i = len;
                    break;
                }
                i = nlp - buf;
                continue;  // process the newline next iteration
            }
        }
        ++i;
    }
    if (overflow) {
        *consumed = row_begin;
        if (row_start)
            row_start[row] = (int32_t)row_begin;
        return row;  // complete rows so far; caller re-feeds the rest
    }
    *consumed = row_begin;
    if (final_block && row_begin < len) {
        int64_t rl = len - row_begin;
        if (rl == 0 || (rl == 1 && buf[row_begin] == '\r')) {
            *consumed = len;  // trailing blank: consumed, no record
        } else if (row < max_rows) {
            // trailing record without newline
            if (k < ncols && col_idx[k] == field) {
                starts[(int64_t)k * max_rows + row] =
                    (int32_t)field_start;
                lens[(int64_t)k * max_rows + row] =
                    (int32_t)(len - field_start);
            }
            if (row_start)
                row_start[row] = (int32_t)row_begin;
            ++row;
            *consumed = len;
        }
    }
    if (row_start)
        row_start[row] = (int32_t)(*consumed);
    return row;
}

// Structural scan of one block.  Returns the number of complete rows
// scanned (possibly fewer than the block holds when max_rows is hit —
// *consumed tells the caller where to resume), or -2 on an unterminated
// quote in the final block.
// *consumed = bytes of buf covered by the returned records.
int64_t sel_csv_scan(const char *buf, int64_t len, char delim, char quote,
                     int final_block,
                     const int32_t *col_idx, int32_t ncols,
                     int64_t max_rows,
                     int32_t *starts, int32_t *lens,
                     int32_t *row_start, int64_t *consumed) {
    if (memchr(buf, quote, len) == nullptr)
        return csv_scan_fast(buf, len, delim, final_block, col_idx, ncols,
                             max_rows, starts, lens, row_start, consumed);
    const char *p = buf, *end = buf + len;
    int64_t row = 0;
    *consumed = 0;
    while (p < end) {
        if (row >= max_rows)
            break;
        const char *rec = p;
        int32_t field = 0, k = 0;
        // pre-fill this row's needed columns as missing
        for (int32_t c = 0; c < ncols; ++c)
            lens[(int64_t)c * max_rows + row] = -1;
        int done_row = 0;
        while (!done_row) {
            int32_t cs, ce;  // logical cell extent
            int esc = 0;
            if (p < end && *p == quote) {
                ++p;
                const char *q = p;
                for (;;) {
                    const char *h = static_cast<const char *>(
                        memchr(q, quote, end - q));
                    if (!h) {
                        if (final_block)
                            return -2;  // unterminated quote
                        goto incomplete;
                    }
                    if (h + 1 < end && h[1] == quote) {
                        esc = 1;
                        q = h + 2;
                        continue;
                    }
                    if (h + 1 == end && !final_block)
                        goto incomplete;  // closing vs doubled: unknown
                    cs = (int32_t)(p - buf);
                    ce = (int32_t)(h - buf);
                    p = h + 1;
                    break;
                }
                // after closing quote: delimiter, newline, or EOF
                if (p < end && *p != delim && *p != '\n' && *p != '\r') {
                    // junk after quote: treat rest as part of the cell
                    const char *j = scan2(p, end, delim, '\n');
                    if (j == end && !final_block)
                        goto incomplete;
                    ce = (int32_t)(j - buf);
                    esc = 1;  // Python csv semantics differ: defer
                    p = j;
                }
            } else {
                const char *st = p;
                const char *j = scan2(p, end, delim, '\n');
                if (j == end && !final_block)
                    goto incomplete;
                cs = (int32_t)(st - buf);
                ce = (int32_t)(j - buf);
                if (ce > cs && buf[ce - 1] == '\r' &&
                    (j < end && *j == '\n'))
                    --ce;  // \r\n record delimiter
                p = j;
            }
            if (k < ncols && col_idx[k] == field) {
                starts[(int64_t)k * max_rows + row] = cs;
                lens[(int64_t)k * max_rows + row] =
                    esc ? -2 : (ce - cs);
                ++k;
            }
            ++field;
            if (p >= end) {
                if (!final_block)
                    goto incomplete;
                done_row = 1;  // final record without trailing newline
            } else if (*p == '\n') {
                ++p;
                done_row = 1;
            } else {
                ++p;  // delimiter
            }
        }
        {
            // blank record (empty line, or lone \r): csv.reader skips
            const char *rend = p;
            if (rend > rec && rend[-1] == '\n')
                --rend;
            int64_t rl = rend - rec;
            if (rl == 0 || (rl == 1 && *rec == '\r')) {
                for (int32_t cc = 0; cc < k; ++cc)
                    lens[(int64_t)cc * max_rows + row] = -1;
                *consumed = p - buf;
                continue;
            }
        }
        row_start[row] = (int32_t)(rec - buf);
        ++row;
        *consumed = p - buf;
        continue;
    incomplete:
        break;
    }
    row_start[row] = (int32_t)(*consumed);
    return row;
}

// --------------------------------------------------------- row emission

// Copy matched rows (verbatim, including their newline) into outbuf.
// Used for `SELECT * ... WHERE` over quote-free CSV when the output
// serialization matches the input (records pass through byte-exact).
// limit < 0 means unlimited.  Returns rows emitted; *out_len = bytes.
int64_t sel_emit_rows(const char *buf, const int32_t *row_start,
                      int64_t nrows, const uint8_t *mask, int64_t limit,
                      char *outbuf, int64_t *out_len) {
    int64_t n = 0, o = 0;
    for (int64_t r = 0; r < nrows; ++r) {
        if (mask && !mask[r])
            continue;
        if (limit >= 0 && n >= limit)
            break;
        int32_t a = row_start[r], b = row_start[r + 1];
        memcpy(outbuf + o, buf + a, b - a);
        o += b - a;
        if (b > a && outbuf[o - 1] != '\n')
            outbuf[o++] = '\n';  // final record without trailing newline
        ++n;
    }
    *out_len = o;
    return n;
}

// ------------------------------------------------- scalar cell functions
//
// The WHERE-leaf language extends to `fn(col) <op> literal` for the
// common scalar functions.  Transforms are exact for ASCII cells;
// anything containing a byte >= 0x80 (multibyte text whose case/space
// rules Python applies per codepoint) flags AMBIGUOUS so the block
// replays through the row engine — same contract as numeric parsing.
// fn codes: 0 none, 1 LOWER, 2 UPPER, 3 TRIM, 4 LTRIM, 5 RTRIM,
// 6 CHAR_LENGTH (cell becomes its codepoint count, compared
// numerically).
enum { FN_NONE = 0, FN_LOWER, FN_UPPER, FN_TRIM, FN_LTRIM, FN_RTRIM,
       FN_CHARLEN, FN_SUBSTR };
// FN_SUBSTR takes (start, len) via the fn_a/fn_b kernel params:
// Python s[max(start-1,0) : max(start-1,0)+len]; fb == -1 is the
// driver's 'no length' sentinel (slice to end) — explicit negative
// lengths never reach here (they fall back: Python-slice semantics).
// Codepoint indexing == byte indexing for the ASCII-only fast path.

static inline int all_ascii(const char *s, int32_t n) {
    for (int32_t i = 0; i < n; ++i)
        if ((unsigned char)s[i] >= 0x80)
            return 0;
    return 1;
}

// Python str.isspace() over ASCII: \t \n \v \f \r space AND the
// C0 separators \x1c-\x1f (str.strip() removes all of them)
static inline int py_space(char c) {
    unsigned char u = (unsigned char)c;
    return c == ' ' || (u >= 0x09 && u <= 0x0D) ||
           (u >= 0x1C && u <= 0x1F);
}

// Apply fn to [s, s+n) into scratch (capacity >= n).  Returns new
// length, or -1 when ambiguous (non-ASCII byte present).
static inline int32_t apply_fn(int fn, const char *s, int32_t n,
                               char *scratch, int32_t fa, int32_t fb) {
    if (!all_ascii(s, n))
        return -1;  // Python unicode semantics: replay
    const char *b = s, *e = s + n;
    switch (fn) {
    case FN_SUBSTR: {
        int32_t start0 = fa - 1;
        if (start0 < 0)
            start0 = 0;
        if (start0 > n)
            start0 = n;
        int32_t take = (fb < 0) ? (n - start0) : fb;
        if (take > n - start0)
            take = n - start0;
        if (take < 0)
            take = 0;
        memcpy(scratch, s + start0, take);
        return take;
    }
    case FN_TRIM:
    case FN_LTRIM:
        while (b < e && py_space(*b))
            ++b;
        if (fn == FN_LTRIM) {
            memcpy(scratch, b, e - b);
            return (int32_t)(e - b);
        }
        /* fallthrough for TRIM's right side */
        [[fallthrough]];
    case FN_RTRIM:
        if (fn == FN_RTRIM)
            b = s;
        while (e > b && py_space(e[-1]))
            --e;
        memcpy(scratch, b, e - b);
        return (int32_t)(e - b);
    case FN_LOWER:
        for (int32_t i = 0; i < n; ++i) {
            char c = s[i];
            scratch[i] = (c >= 'A' && c <= 'Z') ? (char)(c + 32) : c;
        }
        return n;
    case FN_UPPER:
        for (int32_t i = 0; i < n; ++i) {
            char c = s[i];
            scratch[i] = (c >= 'a' && c <= 'z') ? (char)(c - 32) : c;
        }
        return n;
    }
    memcpy(scratch, s, n);
    return n;
}

#define FN_SCRATCH 4096  // cells longer than this replay (rare)

// Comparison ops: 0 '=', 1 '!=', 2 '<', 3 '<=', 4 '>', 5 '>='
static inline int cmp_ok(int op, int c) {
    switch (op) {
    case 0: return c == 0;
    case 1: return c != 0;
    case 2: return c < 0;
    case 3: return c <= 0;
    case 4: return c > 0;
    case 5: return c >= 0;
    }
    return 0;
}

static inline int bytes_cmp(const char *a, int32_t an,
                            const char *b, int32_t bn) {
    int32_t n = an < bn ? an : bn;
    int c = n ? memcmp(a, b, n) : 0;
    if (c)
        return c < 0 ? -1 : 1;
    return an < bn ? -1 : (an > bn ? 1 : 0);
}

// Numeric-literal comparison leaf: cells that parse numerically compare
// against num_lit; everything else (including empty) compares textually
// against str_lit, replicating sql._cmp_pair.  Returns count of
// AMBIGUOUS cells (0 => mask is exact).
int64_t sel_cmp_num(const char *buf, const int32_t *starts,
                    const int32_t *lens, int64_t n, int op,
                    double num_lit, const char *str_lit, int32_t str_len,
                    uint8_t *mask, int fn, int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    const int opmask = OPMASK[op];
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i) {
        int32_t l = lens[i];
        const char *s = buf + starts[i];
        double v;
        if (fn == FN_CHARLEN) {
            if (l < 0) {
                mask[i] = 0;
                if (l == -2)
                    ++amb;
                continue;
            }
            if (!all_ascii(s, l)) {  // codepoint counting: Python decides
                mask[i] = 0;
                ++amb;
                continue;
            }
            int c = ((double)l > num_lit) - ((double)l < num_lit);
            mask[i] = (uint8_t)((opmask >> (c + 1)) & 1);
            continue;
        }
        if (fn != FN_NONE && l > 0) {
            if (l > FN_SCRATCH) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
            if (nl < 0) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            s = scratch;
            l = nl;
        }
        // hot path: short pure-digit cell, fully inlined SWAR
        if ((uint32_t)(l - 1) < 8u && parse_int8_swar(s, l, &v)) {
            int c = (v > num_lit) - (v < num_lit);
            mask[i] = (uint8_t)((opmask >> (c + 1)) & 1);
            continue;
        }
        if (l < 0) {
            mask[i] = 0;  // null (or needs-unquote: caller pre-screens)
            if (l == -2)
                ++amb;
            continue;
        }
        if (parse_num(s, l, &v)) {
            int c = (v > num_lit) - (v < num_lit);
            mask[i] = (uint8_t)((opmask >> (c + 1)) & 1);
        } else if (num_ambiguous(s, l)) {
            mask[i] = 0;
            ++amb;
        } else {
            mask[i] = (uint8_t)cmp_ok(op, bytes_cmp(s, l, str_lit,
                                                    str_len));
        }
    }
    return amb;
}

// Text-literal comparison leaf: pure byte compare (UTF-8 order == code
// point order).  Cells are never ambiguous here except -2 (unquote).
int64_t sel_cmp_str(const char *buf, const int32_t *starts,
                    const int32_t *lens, int64_t n, int op,
                    const char *lit, int32_t lit_len, uint8_t *mask,
                    int fn, int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i) {
        int32_t l = lens[i];
        const char *s = buf + starts[i];
        if (l < 0) {
            mask[i] = 0;
            if (l == -2)
                ++amb;
            continue;
        }
        if (fn == FN_CHARLEN) {
            // text compare of the DECIMAL rendering of the length
            if (!all_ascii(s, l)) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            int32_t nl = (int32_t)snprintf(scratch, 16, "%d", l);
            s = scratch;
            l = nl;
        } else if (fn != FN_NONE && l > 0) {
            if (l > FN_SCRATCH) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
            if (nl < 0) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            s = scratch;
            l = nl;
        }
        mask[i] = (uint8_t)cmp_ok(op, bytes_cmp(s, l, lit, lit_len));
    }
    return amb;
}

// LIKE leaf.  negate handled by the Python driver (needs the valid
// mask).  lit[] marks pattern bytes that are literals (escape-resolved).
int64_t sel_like(const char *buf, const int32_t *starts,
                 const int32_t *lens, int64_t n,
                 const char *pat, int32_t pat_len,
                 const unsigned char *lit, uint8_t *mask, int fn,
                 int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i) {
        int32_t l = lens[i];
        const char *s = buf + starts[i];
        if (l < 0) {
            mask[i] = 0;
            if (l == -2)
                ++amb;
            continue;
        }
        if (fn != FN_NONE && l > 0) {
            if (l > FN_SCRATCH || fn == FN_CHARLEN) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
            if (nl < 0) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            s = scratch;
            l = nl;
        }
        mask[i] = (uint8_t)like_match(s, l, pat, pat_len, lit);
    }
    return amb;
}

// Validity mask: 1 where the cell exists (len >= 0).  -2 counts as
// existing but ambiguous.
void sel_valid(const int32_t *lens, int64_t n, uint8_t *mask) {
    for (int64_t i = 0; i < n; ++i)
        mask[i] = lens[i] >= 0 || lens[i] == -2;
}

// IS NULL mask: missing column or empty text (row engine: None or "").
void sel_isnull(const int32_t *lens, int64_t n, uint8_t *mask) {
    for (int64_t i = 0; i < n; ++i)
        mask[i] = lens[i] == -1 || lens[i] == 0;
}

// Aggregate fold over one column under an optional row mask.
// agg op: 0 COUNT, 1 SUM/AVG, 2 MIN/MAX (tracks argmin/argmax).
// Returns count of cells folded; *amb counts ambiguous cells (caller
// re-runs the block in Python when nonzero).  For SUM a non-numeric
// non-empty cell is ambiguous (the row engine raises SQLError — the
// Python replay reproduces that exactly).
int64_t sel_agg(const char *buf, const int32_t *starts,
                const int32_t *lens, int64_t n, const uint8_t *mask,
                int what, double *sum, double *minv, double *maxv,
                int64_t *argmin, int64_t *argmax, int64_t *amb) {
    int64_t cnt = 0;
    *amb = 0;
    double s = 0.0;
    double lo = 0.0, hi = 0.0;
    int64_t ilo = -1, ihi = -1;
    for (int64_t i = 0; i < n; ++i) {
        if (mask && !mask[i])
            continue;
        int32_t l = lens[i];
        if (l == -1 || l == 0)
            continue;  // null/empty: skipped by accumulate
        if (l == -2) {
            ++*amb;
            continue;
        }
        if (what == 0) {
            ++cnt;
            continue;
        }
        double v;
        if (!parse_num(buf + starts[i], l, &v)) {
            ++*amb;  // SUM raises / MIN-MAX mixes text: Python decides
            continue;
        }
        ++cnt;
        if (what == 1) {
            s += v;
        } else {
            if (ilo < 0 || v < lo) {
                lo = v;
                ilo = i;
            }
            if (ihi < 0 || v > hi) {
                hi = v;
                ihi = i;
            }
        }
    }
    *sum = s;
    *minv = lo;
    *maxv = hi;
    *argmin = ilo;
    *argmax = ihi;
    return cnt;
}

// ------------------------------------------------------ column emission

// Emit selected columns of masked rows as CSV records (projection
// path: SELECT a,b ... WHERE).  Caller guarantees the block is free of
// quote chars and \r (blocks containing either replay through the row
// engine's csv.writer), so cells copy verbatim: no quoting can ever be
// required — cells cannot contain the delimiter or newline by
// construction.  Missing cells (len -1, ragged rows) emit empty, the
// row engine's rendering of a None projection.  limit < 0 = unlimited.
// Returns rows emitted; *out_len = bytes written.
int64_t sel_emit_cols(const char *buf, const int32_t *starts,
                      const int32_t *lens, int64_t max_rows,
                      const int32_t *slots, int32_t nslots,
                      int64_t nrows, const uint8_t *mask, int64_t limit,
                      char delim, char *outbuf, int64_t *out_len) {
    int64_t n = 0, o = 0;
    for (int64_t r = 0; r < nrows; ++r) {
        if (mask && !mask[r])
            continue;
        if (limit >= 0 && n >= limit)
            break;
        for (int32_t c = 0; c < nslots; ++c) {
            if (c)
                outbuf[o++] = delim;
            int64_t idx = (int64_t)slots[c] * max_rows + r;
            int32_t l = lens[idx];
            if (l > 0) {
                memcpy(outbuf + o, buf + starts[idx], l);
                o += l;
            }
        }
        outbuf[o++] = '\n';
        ++n;
    }
    *out_len = o;
    return n;
}

// ---------------------------------------------- numeric expression leaves

// Tiny per-cell numeric program for `expr(col) <op> literal` leaves
// where expr is an arithmetic/CAST chain over ONE column:
//   codes: 0 x+k, 1 x-k, 2 x*k, 3 x/k, 4 x%k (Python floor-sign mod),
//          5 k-x, 6 k/x, 7 trunc(x) (CAST INT), 8 noop (CAST FLOAT)
// A cell that fails the strict numeric parse is AMBIGUOUS (the row
// engine raises SQLError for arithmetic on non-numbers — the replay
// reproduces that exactly), as are div/mod by zero.
static inline int run_prog(double x, const int32_t *codes,
                           const double *ops, int plen, double *out) {
    for (int p = 0; p < plen; ++p) {
        double k = ops[p];
        switch (codes[p]) {
        case 0: x = x + k; break;
        case 1: x = x - k; break;
        case 2: x = x * k; break;
        case 3:
            if (k == 0.0)
                return 0;
            x = x / k;
            break;
        case 4: {
            if (k == 0.0)
                return 0;
            double r = fmod(x, k);
            if (r != 0.0 && ((r < 0.0) != (k < 0.0)))
                r += k;  // Python floor-sign modulo
            x = r;
            break;
        }
        case 5: x = k - x; break;
        case 6:
            if (x == 0.0)
                return 0;
            x = k / x;
            break;
        case 7: x = trunc(x); break;
        case 8: break;
        }
        // Exactness guard: beyond 2^53 the row engine's Python big-int
        // arithmetic diverges from doubles, and NaN/inf compare under
        // different rules (NaN cmp is always False in Python; the
        // 3-way compare here would read it as 'equal').  Both fail
        // this bound (NaN fails every comparison) => replay.
        if (!(x > -9007199254740992.0 && x < 9007199254740992.0))
            return 0;
    }
    *out = x;
    return 1;
}

int64_t sel_cmp_expr(const char *buf, const int32_t *starts,
                     const int32_t *lens, int64_t n, int op,
                     double num_lit, const int32_t *codes,
                     const double *ops, int plen, uint8_t *mask) {
    int64_t amb = 0;
    const int opmask = OPMASK[op];
    for (int64_t i = 0; i < n; ++i) {
        int32_t l = lens[i];
        const char *s = buf + starts[i];
        double v;
        if (l < 0 || !parse_num(s, l, &v) ||
            !run_prog(v, codes, ops, plen, &v)) {
            // null/missing/garbage cells: the row engine RAISES for
            // arithmetic — replay the block so it can
            mask[i] = 0;
            ++amb;
            continue;
        }
        int c = (v > num_lit) - (v < num_lit);
        mask[i] = (uint8_t)((opmask >> (c + 1)) & 1);
    }
    return amb;
}

int64_t sel_json_cmp_expr(const char *buf, const int32_t *starts,
                          const int32_t *lens, const uint8_t *types,
                          int64_t n, int op, double num_lit,
                          const int32_t *codes, const double *ops,
                          int plen, uint8_t *mask) {
    int64_t amb = 0;
    const int opmask = OPMASK[op];
    for (int64_t i = 0; i < n; ++i) {
        uint8_t t = types[i];
        double v;
        // number tokens and numeric strings both feed arithmetic in
        // the row engine (_num coerces); everything else raises there
        if ((t != 4 && t != 5) ||
            !parse_num(buf + starts[i], lens[i], &v) ||
            !run_prog(v, codes, ops, plen, &v)) {
            mask[i] = 0;
            ++amb;
            continue;
        }
        int c = (v > num_lit) - (v < num_lit);
        mask[i] = (uint8_t)((opmask >> (c + 1)) & 1);
    }
    return amb;
}

// ------------------------------------------------------------ NDJSON scan

// Per-line top-level key extraction.  For each needed key the scanner
// records the value extent and a type code:
//   0 missing, 1 null, 2 false, 3 true, 4 number, 5 string (no escapes,
//   extent = inner bytes), 6 ambiguous (string w/ escapes, nested
//   object/array, any parse doubt)
// A line that cannot be cleanly parsed sets every needed key on that
// row to 6 — the Python driver re-evaluates such rows exactly (and the
// row engine raises on truly invalid JSON, preserving error semantics).

static inline const char *skip_ws(const char *q, const char *le) {
    while (q < le && (*q == ' ' || *q == '\t' || *q == '\r'))
        ++q;
    return q;
}

// SWAR single-byte finder: cheaper than a memchr call for the short
// hops typical of compact JSON (keys and values of a few bytes).
// Returns le when absent.
__attribute__((always_inline))
static inline const char *find_byte(const char *p, const char *le,
                                    char c) {
    const uint64_t pat = 0x0101010101010101ULL * (unsigned char)c;
    while (p + 8 <= le) {
        uint64_t x;
        memcpy(&x, p, 8);
        uint64_t v = x ^ pat;
        uint64_t hit = (v - 0x0101010101010101ULL) & ~v &
                       0x8080808080808080ULL;
        if (hit)
            return p + (__builtin_ctzll(hit) >> 3);
        p += 8;
    }
    while (p < le && *p != c)
        ++p;
    return p;
}

// Fast parse of one line KNOWN to contain no backslash: every '"' is a
// real string boundary.  Returns 0 on clean parse, 1 when the line
// needs the slow machine (or is invalid).
static int json_line_fast(const char *buf, const char *ls, const char *le,
                          const char *const *keys, const int32_t *key_lens,
                          int32_t nkeys, int64_t max_rows, int64_t row,
                          int32_t *starts, int32_t *lens, uint8_t *types) {
    const char *q = ls;
    if (*q != '{')
        return 1;
    q = skip_ws(q + 1, le);
    if (q < le && *q == '}')
        return skip_ws(q + 1, le) == le ? 0 : 1;
    for (;;) {
        if (q >= le || *q != '"')
            return 1;
        const char *ks = q + 1;
        const char *kq = find_byte(ks, le, '"');
        if (kq == le)
            return 1;
        int32_t klen = (int32_t)(kq - ks);
        q = skip_ws(kq + 1, le);
        if (q >= le || *q != ':')
            return 1;
        q = skip_ws(q + 1, le);
        if (q >= le)
            return 1;
        int ki = -1;
        for (int32_t k = 0; k < nkeys; ++k)
            if (key_lens[k] == klen &&
                (klen == 0 || (keys[k][0] == ks[0] &&
                               memcmp(keys[k], ks, klen) == 0))) {
                ki = k;
                break;
            }
        uint8_t vt;
        int32_t vs = (int32_t)(q - buf), vl;
        char v0 = *q;
        if (v0 == '"') {
            const char *ss = q + 1;
            const char *sq = find_byte(ss, le, '"');
            if (sq == le)
                return 1;
            vt = 5;
            vs = (int32_t)(ss - buf);
            vl = (int32_t)(sq - ss);
            q = sq + 1;
        } else if (v0 == '{' || v0 == '[') {
            int d = 0;
            const char *z = q;
            while (z < le) {
                char c = *z;
                if (c == '"') {
                    const char *t = static_cast<const char *>(
                        memchr(z + 1, '"', le - z - 1));
                    if (!t)
                        return 1;
                    z = t + 1;
                    continue;
                }
                if (c == '{' || c == '[') {
                    ++d;
                } else if (c == '}' || c == ']') {
                    --d;
                    if (d == 0) {
                        ++z;
                        break;
                    }
                }
                ++z;
            }
            if (d != 0)
                return 1;
            vt = 6;  // nested value: Python semantics if needed
            vl = (int32_t)(z - q);
            q = z;
        } else if (v0 == 't') {
            if (le - q < 4 || memcmp(q, "true", 4) != 0)
                return 1;
            vt = 3;
            vl = 4;
            q += 4;
        } else if (v0 == 'f') {
            if (le - q < 5 || memcmp(q, "false", 5) != 0)
                return 1;
            vt = 2;
            vl = 5;
            q += 5;
        } else if (v0 == 'n') {
            if (le - q < 4 || memcmp(q, "null", 4) != 0)
                return 1;
            vt = 1;
            vl = 4;
            q += 4;
        } else {
            const char *z = q;
            while (z < le && *z != ',' && *z != '}' && *z != ' ' &&
                   *z != '\t' && *z != '\r')
                ++z;
            vl = (int32_t)(z - q);
            double dummy;
            if (!parse_num(q, vl, &dummy))
                return 1;  // big ints / garbage: slow machine decides
            vt = 4;
            q = z;
        }
        if (ki >= 0) {  // last occurrence wins (json.loads semantics)
            starts[(int64_t)ki * max_rows + row] = vs;
            lens[(int64_t)ki * max_rows + row] = vl;
            types[(int64_t)ki * max_rows + row] = vt;
        }
        q = skip_ws(q, le);
        if (q < le && *q == ',') {
            q = skip_ws(q + 1, le);
            continue;
        }
        if (q < le && *q == '}') {
            q = skip_ws(q + 1, le);
            return q == le ? 0 : 1;
        }
        return 1;
    }
}

// Slow per-line machine: handles escapes; anything it cannot cleanly
// type marks the row ambiguous (types = 6 across the board).
static void json_line_slow(const char *buf, const char *ls, const char *le,
                           const char *const *keys, const int32_t *key_lens,
                           int32_t nkeys, int64_t max_rows, int64_t row,
                           int32_t *starts, int32_t *lens, uint8_t *types) {
    int bad = 0;
    const char *q = ls;
    if (*q != '{') {
        bad = 1;  // non-object line (array/scalar): row engine wraps
    } else {
        ++q;
        int depth = 1;
        while (q < le && depth > 0 && !bad) {
            char c = *q;
            if (c == ' ' || c == '\t' || c == '\r') {
                ++q;
                continue;
            }
            if (c == '}') {
                --depth;
                ++q;
                continue;
            }
            if (c != '"') {
                bad = 1;
                break;
            }
            // key string
            const char *ks = q + 1;
            const char *kq = ks;
            int kesc = 0;
            for (;;) {
                const char *h = static_cast<const char *>(
                    memchr(kq, '"', le - kq));
                if (!h) {
                    bad = 1;
                    break;
                }
                int bs = 0;
                const char *t = h - 1;
                while (t >= ks && *t == '\\') {
                    ++bs;
                    --t;
                }
                if (bs % 2) {
                    kesc = 1;
                    kq = h + 1;
                    continue;
                }
                kq = h;
                break;
            }
            if (bad)
                break;
            if (kesc) {
                bad = 1;  // escaped key text: let Python decide
                break;
            }
            int32_t klen = (int32_t)(kq - ks);
            q = skip_ws(kq + 1, le);
            if (q >= le || *q != ':') {
                bad = 1;
                break;
            }
            q = skip_ws(q + 1, le);
            if (q >= le) {
                bad = 1;
                break;
            }
            int ki = -1;
            for (int32_t k = 0; k < nkeys; ++k)
                if (key_lens[k] == klen &&
                    memcmp(keys[k], ks, klen) == 0) {
                    ki = k;
                    break;
                }
            uint8_t vt = 6;
            int32_t vs = (int32_t)(q - buf), vl = 0;
            char v0 = *q;
            if (v0 == '"') {
                const char *ss = q + 1;
                const char *sq = ss;
                int sesc = 0;
                for (;;) {
                    const char *h = static_cast<const char *>(
                        memchr(sq, '"', le - sq));
                    if (!h) {
                        bad = 1;
                        break;
                    }
                    int bs = 0;
                    const char *t = h - 1;
                    while (t >= ss && *t == '\\') {
                        ++bs;
                        --t;
                    }
                    if (bs % 2) {
                        sesc = 1;
                        sq = h + 1;
                        continue;
                    }
                    sq = h;
                    break;
                }
                if (bad)
                    break;
                vt = sesc ? 6 : 5;
                vs = (int32_t)(ss - buf);
                vl = (int32_t)(sq - ss);
                q = sq + 1;
            } else if (v0 == '{' || v0 == '[') {
                int d2 = 0;
                int instr = 0;
                const char *z = q;
                while (z < le) {
                    char c2 = *z;
                    if (instr) {
                        if (c2 == '\\') {
                            z += 2;
                            continue;
                        }
                        if (c2 == '"')
                            instr = 0;
                    } else if (c2 == '"') {
                        instr = 1;
                    } else if (c2 == '{' || c2 == '[') {
                        ++d2;
                    } else if (c2 == '}' || c2 == ']') {
                        --d2;
                        if (d2 == 0) {
                            ++z;
                            break;
                        }
                    }
                    ++z;
                }
                if (d2 != 0) {
                    bad = 1;
                    break;
                }
                vt = 6;  // nested: Python semantics
                vs = (int32_t)(q - buf);
                vl = (int32_t)(z - q);
                q = z;
            } else if (v0 == 't' && le - q >= 4 &&
                       memcmp(q, "true", 4) == 0) {
                vt = 3;
                vl = 4;
                q += 4;
            } else if (v0 == 'f' && le - q >= 5 &&
                       memcmp(q, "false", 5) == 0) {
                vt = 2;
                vl = 5;
                q += 5;
            } else if (v0 == 'n' && le - q >= 4 &&
                       memcmp(q, "null", 4) == 0) {
                vt = 1;
                vl = 4;
                q += 4;
            } else {
                const char *z = q;
                while (z < le && *z != ',' && *z != '}' && *z != ' ' &&
                       *z != '\t' && *z != '\r')
                    ++z;
                double dummy;
                vl = (int32_t)(z - q);
                if (!parse_num(q, vl, &dummy)) {
                    // invalid bare token OR >15-digit int: the row
                    // engine either raises or parses exactly — replay
                    bad = 1;
                    break;
                }
                vt = 4;
                q = z;
            }
            if (ki >= 0) {
                starts[(int64_t)ki * max_rows + row] = vs;
                lens[(int64_t)ki * max_rows + row] = vl;
                types[(int64_t)ki * max_rows + row] = vt;
            }
            q = skip_ws(q, le);
            if (q < le && *q == ',') {
                ++q;
                continue;
            }
            if (q < le && *q == '}') {
                --depth;
                ++q;
                continue;
            }
            bad = 1;
            break;
        }
        if (depth != 0)
            bad = 1;
        if (skip_ws(q, le) != le)
            bad = 1;  // trailing junk after the closing brace
    }
    if (bad)
        for (int32_t k = 0; k < nkeys; ++k)
            types[(int64_t)k * max_rows + row] = 6;
}

// Returns rows scanned (complete lines; may stop early at max_rows with
// *consumed marking the resume point).  Blank lines are skipped (row
// engine skips them too).
int64_t sel_json_scan(const char *buf, int64_t len, int final_block,
                      const char *const *keys, const int32_t *key_lens,
                      int32_t nkeys, int64_t max_rows,
                      int32_t *starts, int32_t *lens, uint8_t *types,
                      int32_t *row_start, int32_t *row_len,
                      int64_t *consumed) {
    const char *p = buf, *end = buf + len;
    int64_t row = 0;
    *consumed = 0;
    // one block-level probe: no backslash anywhere => every line takes
    // the memchr-driven fast parser without per-line escape checks
    const int bs_block = memchr(buf, '\\', len) != nullptr;
    while (p < end) {
        const char *nlp = find_byte(p, end, '\n');
        const char *nl = (nlp == end) ? nullptr : nlp;
        const char *line_end;
        if (nl == nullptr) {
            if (!final_block)
                break;  // incomplete trailing line
            line_end = end;
        } else {
            line_end = nl;
        }
        const char *ls = p, *le = line_end;
        while (ls < le && (*ls == ' ' || *ls == '\t' || *ls == '\r'))
            ++ls;
        while (le > ls && (le[-1] == ' ' || le[-1] == '\t' ||
                           le[-1] == '\r'))
            --le;
        if (ls == le) {  // blank line
            p = (nl ? nl + 1 : end);
            *consumed = p - buf;
            continue;
        }
        if (row >= max_rows)
            break;
        for (int32_t k = 0; k < nkeys; ++k)
            types[(int64_t)k * max_rows + row] = 0;  // missing (starts/
        // lens are only read for types >= 4, so no prefill needed)
        row_start[row] = (int32_t)(ls - buf);
        row_len[row] = (int32_t)(le - ls);
        int need_slow = 1;
        if (!bs_block || memchr(ls, '\\', le - ls) == nullptr)
            need_slow = json_line_fast(buf, ls, le, keys, key_lens, nkeys,
                                       max_rows, row, starts, lens, types);
        if (need_slow)
            json_line_slow(buf, ls, le, keys, key_lens, nkeys,
                           max_rows, row, starts, lens, types);
        ++row;
        p = (nl ? nl + 1 : end);
        *consumed = p - buf;
    }
    row_start[row] = (int32_t)(*consumed);
    return row;
}

// JSON numeric-literal comparison: number cells (type 4) and
// numeric-looking string cells (type 5) compare numerically; string
// cells that don't parse compare textually; bool/null/ambiguous per
// row-engine rules.  Text compare of a NUMBER cell is ambiguous
// (Python renders str(5.00) as "5.0" — raw bytes may differ).
int64_t sel_json_cmp(const char *buf, const int32_t *starts,
                     const int32_t *lens, const uint8_t *types,
                     int64_t n, int op, double num_lit, int lit_is_num,
                     const char *str_lit, int32_t str_len,
                     uint8_t *mask, int fn, int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    const int opmask = OPMASK[op];
    for (int64_t i = 0; i < n; ++i) {
        uint8_t t = types[i];
        if (t == 0 || t == 1) {  // missing/null: compare is false
            mask[i] = 0;
            continue;
        }
        if (t == 6 || t == 2 || t == 3) {  // ambiguous or bool
            mask[i] = 0;
            ++amb;
            continue;
        }
        const char *s = buf + starts[i];
        int32_t l = lens[i];
        if (fn != FN_NONE) {
            if (t != 5) {  // fn over a number cell: str() rendering
                mask[i] = 0;
                ++amb;
                continue;
            }
            if (fn == FN_CHARLEN) {
                if (!all_ascii(s, l)) {
                    mask[i] = 0;
                    ++amb;
                    continue;
                }
                if (lit_is_num) {
                    int c = ((double)l > num_lit) - ((double)l < num_lit);
                    mask[i] = (uint8_t)((opmask >> (c + 1)) & 1);
                } else {
                    int32_t nl = (int32_t)snprintf(scratch, 16, "%d", l);
                    mask[i] = (uint8_t)cmp_ok(
                        op, bytes_cmp(scratch, nl, str_lit, str_len));
                }
                continue;
            }
            if (l > FN_SCRATCH) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
            if (nl < 0) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            s = scratch;
            l = nl;
        }
        if (t == 4) {  // fn != NONE already continued above for t != 5
            if (!lit_is_num) {  // text compare of number cell: rendering
                mask[i] = 0;
                ++amb;
                continue;
            }
            double v;
            if (!parse_num(s, l, &v)) {  // huge ints etc.
                mask[i] = 0;
                ++amb;
                continue;
            }
            int c = v < num_lit ? -1 : (v > num_lit ? 1 : 0);
            mask[i] = (uint8_t)cmp_ok(op, c);
            continue;
        }
        // string cell
        double v;
        if (lit_is_num && parse_num(s, l, &v)) {
            int c = v < num_lit ? -1 : (v > num_lit ? 1 : 0);
            mask[i] = (uint8_t)cmp_ok(op, c);
        } else if (lit_is_num && num_ambiguous(s, l)) {
            mask[i] = 0;
            ++amb;
        } else {
            mask[i] = (uint8_t)cmp_ok(op, bytes_cmp(s, l, str_lit,
                                                    str_len));
        }
    }
    return amb;
}

// JSON LIKE: string cells only (row engine str()s other types —
// ambiguous).  Missing/null => false.
int64_t sel_json_like(const char *buf, const int32_t *starts,
                      const int32_t *lens, const uint8_t *types,
                      int64_t n, const char *pat, int32_t pat_len,
                      const unsigned char *lit, uint8_t *mask, int fn,
                 int32_t fn_a, int32_t fn_b) {
    int64_t amb = 0;
    char scratch[FN_SCRATCH];
    for (int64_t i = 0; i < n; ++i) {
        uint8_t t = types[i];
        if (t == 0 || t == 1) {
            mask[i] = 0;
            continue;
        }
        if (t != 5) {
            mask[i] = 0;
            ++amb;
            continue;
        }
        const char *s = buf + starts[i];
        int32_t l = lens[i];
        if (fn != FN_NONE) {
            if (l > FN_SCRATCH || fn == FN_CHARLEN) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            int32_t nl = apply_fn(fn, s, l, scratch, fn_a, fn_b);
            if (nl < 0) {
                mask[i] = 0;
                ++amb;
                continue;
            }
            s = scratch;
            l = nl;
        }
        mask[i] = (uint8_t)like_match(s, l, pat, pat_len, lit);
    }
    return amb;
}

// JSON validity (for NOT/negate composition): value present and not null.
void sel_json_valid(const uint8_t *types, int64_t n, uint8_t *mask) {
    for (int64_t i = 0; i < n; ++i)
        mask[i] = types[i] != 0 && types[i] != 1;
}

// JSON IS NULL: missing key or null value, or an empty string (row
// engine: v is None or v == "").  Type-6 cells (ambiguous value OR a
// structurally bad line) are counted in the return value so the
// driver replays them — a bad NDJSON line must raise like the row
// engine even when the WHERE is IS NULL-only.
int64_t sel_json_isnull(const int32_t *lens, const uint8_t *types,
                        int64_t n, uint8_t *mask) {
    int64_t amb = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (types[i] == 6) {
            mask[i] = 0;
            ++amb;
            continue;
        }
        mask[i] = types[i] == 0 || types[i] == 1 ||
                  (types[i] == 5 && lens[i] == 0);
    }
    return amb;
}

// JSON aggregate fold (same contract as sel_agg).  Number cells and
// numeric strings fold; bool/nested/escaped => ambiguous; null/missing
// and empty strings skip.
int64_t sel_json_agg(const char *buf, const int32_t *starts,
                     const int32_t *lens, const uint8_t *types,
                     int64_t n, const uint8_t *mask, int what,
                     double *sum, double *minv, double *maxv,
                     int64_t *argmin, int64_t *argmax, int64_t *amb) {
    int64_t cnt = 0;
    *amb = 0;
    double s = 0.0, lo = 0.0, hi = 0.0;
    int64_t ilo = -1, ihi = -1;
    for (int64_t i = 0; i < n; ++i) {
        if (mask && !mask[i])
            continue;
        uint8_t t = types[i];
        if (t == 0 || t == 1)
            continue;  // missing/null
        if (t == 5 && lens[i] == 0)
            continue;  // "" skipped like CSV empty
        if (t == 6 || t == 2 || t == 3) {
            ++*amb;
            continue;
        }
        if (what == 0) {
            ++cnt;
            continue;
        }
        double v;
        if (!parse_num(buf + starts[i], lens[i], &v)) {
            ++*amb;
            continue;
        }
        ++cnt;
        if (what == 1) {
            s += v;
        } else {
            if (ilo < 0 || v < lo) {
                lo = v;
                ilo = i;
            }
            if (ihi < 0 || v > hi) {
                hi = v;
                ihi = i;
            }
        }
    }
    *sum = s;
    *minv = lo;
    *maxv = hi;
    *argmin = ilo;
    *argmax = ihi;
    return cnt;
}

}  // extern "C"
