"""Bucket metadata system: one cached, persisted aggregate per bucket.

Reference: `BucketMetadata` (cmd/bucket-metadata.go:76) persists
policy/lifecycle/sse/tagging/object-lock/quota/notification/replication
configs in one `.metadata.bin` per bucket, fronted by the cached
`BucketMetadataSys` (cmd/bucket-metadata-sys.go) with peer invalidation.

Here the aggregate rides the object layer's bucket-metadata JSON doc
(replicated to every drive's system volume); config payloads are stored
as strings (XML/JSON as the S3 API supplied them) under well-known keys,
parsed on demand and cached parsed-form by generation counter.
"""

from __future__ import annotations

import threading
import time

from minio_tpu.iam.policy import Policy
from minio_tpu.storage import errors

# aggregate keys (values are the raw config documents)
POLICY = "policy"              # JSON policy document
LIFECYCLE = "lifecycle"        # LifecycleConfiguration XML
TAGGING = "tagging"            # Tagging XML
SSE_CONFIG = "sse"             # ServerSideEncryptionConfiguration XML
OBJECT_LOCK = "object_lock"    # ObjectLockConfiguration XML
QUOTA = "quota"                # JSON {"quota": bytes, "quotatype": "hard"}
NOTIFICATION = "notification"  # NotificationConfiguration XML
REPLICATION = "replication"    # ReplicationConfiguration XML
VERSIONING = "versioning"      # bool (managed by set_versioning)
CORS = "cors"                  # raw CORSConfiguration XML


class BucketMetadataSys:
    """Cached view over per-bucket metadata with explicit invalidation."""

    def __init__(self, api):
        self.api = api
        self._lock = threading.Lock()
        self._cache: dict[str, tuple[float, dict]] = {}
        # parsed-config memos: bucket -> (raw doc it was parsed from,
        # parsed form) so hot paths (per-key auth, per-event notification
        # matching) don't reparse per call
        self._policy_parsed: dict[str, tuple[str, Policy | None]] = {}
        self._notif_parsed: dict[str, tuple[str, object]] = {}
        self._cors_parsed: dict[str, tuple[str, object]] = {}
        self._lock_parsed: dict[str, tuple[str, tuple]] = {}
        # peer-broadcast hook set by ClusterNode: fn(bucket) after a
        # config mutation, so other nodes invalidate their caches
        # (reference globalNotificationSys.LoadBucketMetadata)
        self.on_change = None
        # site-replication hook set by SiteReplicationSys (fn(bucket))
        self.on_site_change = None
        self.ttl = 5.0  # seconds; single-node writes invalidate eagerly

    # ------------------------------------------------------------- raw doc
    def get(self, bucket: str) -> dict:
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(bucket)
            if hit and now - hit[0] < self.ttl:
                return hit[1]
        meta = self.api.get_bucket_metadata(bucket)
        with self._lock:
            self._cache[bucket] = (now, meta)
        return meta

    def invalidate(self, bucket: str) -> None:
        with self._lock:
            self._cache.pop(bucket, None)
            self._policy_parsed.pop(bucket, None)
            self._notif_parsed.pop(bucket, None)
            self._cors_parsed.pop(bucket, None)
            self._lock_parsed.pop(bucket, None)

    def changed(self, bucket: str) -> None:
        """Invalidate locally and broadcast to peers."""
        self.invalidate(bucket)
        if self.on_change is not None:
            try:
                self.on_change(bucket)
            except Exception:
                pass  # peers converge via TTL
        if self.on_site_change is not None:
            try:
                self.on_site_change(bucket)
            except Exception:
                pass  # pushes retry from the site worker queue

    def set_config(self, bucket: str, key: str, value) -> None:
        if not self.api.bucket_exists(bucket):
            raise errors.BucketNotFound(bucket)
        self.api.update_bucket_metadata(bucket, **{key: value})
        self.changed(bucket)

    def delete_config(self, bucket: str, key: str) -> None:
        if not self.api.bucket_exists(bucket):
            raise errors.BucketNotFound(bucket)
        meta = self.api.get_bucket_metadata(bucket)
        if key in meta:
            meta.pop(key)
            self.api.set_bucket_metadata(bucket, meta)
        self.changed(bucket)

    def get_config(self, bucket: str, key: str):
        if not self.api.bucket_exists(bucket):
            raise errors.BucketNotFound(bucket)
        return self.get(bucket).get(key)

    # ------------------------------------------------------------ typed views
    def default_retention(self, bucket: str) -> tuple[str, int]:
        """(mode, seconds) of the bucket's object-lock DefaultRetention
        rule, or ('', 0).  Memoized against the raw config — this runs
        on every PUT."""
        raw = self.get(bucket).get(OBJECT_LOCK)
        if not raw:
            return "", 0
        with self._lock:
            hit = self._lock_parsed.get(bucket)
            if hit is not None and hit[0] == raw:
                return hit[1]
        out = ("", 0)
        try:
            import xml.etree.ElementTree as ET

            root = ET.fromstring(raw)
            mode = days = years = None
            for e in root.iter():
                tag = e.tag.rsplit("}", 1)[-1]
                if tag == "Mode":
                    mode = (e.text or "").strip()
                elif tag == "Days":
                    days = int((e.text or "0").strip() or 0)
                elif tag == "Years":
                    years = int((e.text or "0").strip() or 0)
            if mode in ("GOVERNANCE", "COMPLIANCE")                     and not (days and years):
                seconds = (days or 0) * 86400                     + (years or 0) * 365 * 86400
                if seconds > 0:
                    out = (mode, seconds)
        except (ET.ParseError, ValueError):
            out = ("", 0)  # malformed config must never break PUTs
        with self._lock:
            self._lock_parsed[bucket] = (raw, out)
        return out

    def cors(self, bucket: str):
        """Parsed CORSConfig (memoized against the raw doc) or None.
        Served from the TTL cache — the per-response hot path must not
        stat drives or reparse XML."""
        raw = self.get(bucket).get(CORS)
        if not raw:
            return None
        with self._lock:
            hit = self._cors_parsed.get(bucket)
            if hit is not None and hit[0] == raw:
                return hit[1]
        from .cors import CORSError, parse_cors_xml

        try:
            cfg = parse_cors_xml(raw.encode()
                                 if isinstance(raw, str) else raw)
        except CORSError:
            cfg = None
        with self._lock:
            self._cors_parsed[bucket] = (raw, cfg)
        return cfg

    def policy(self, bucket: str) -> Policy | None:
        raw = self.get(bucket).get(POLICY)
        if not raw:
            return None
        with self._lock:
            hit = self._policy_parsed.get(bucket)
            if hit is not None and hit[0] == raw:
                return hit[1]
        try:
            pol = Policy.from_json(raw)
        except Exception:
            pol = None
        with self._lock:
            self._policy_parsed[bucket] = (raw, pol)
        return pol

    def lifecycle(self, bucket: str):
        from . import lifecycle as lc

        raw = self.get(bucket).get(LIFECYCLE)
        if not raw:
            return None
        try:
            return lc.Lifecycle.from_xml(raw)
        except Exception:
            return None

    def quota(self, bucket: str) -> int:
        q = self.get(bucket).get(QUOTA) or {}
        try:
            return int(q.get("quota", 0))
        except (TypeError, AttributeError, ValueError):
            return 0

    def object_lock_enabled(self, bucket: str) -> bool:
        return bool(self.get(bucket).get(OBJECT_LOCK))

    def replication_config(self, bucket: str):
        from . import replication as repl

        raw = self.get(bucket).get(REPLICATION)
        if not raw:
            return None
        try:
            return repl.ReplicationConfig.from_xml(raw)
        except Exception:
            return None

    def notification_config(self, bucket: str):
        from minio_tpu.events import config as ncfg

        raw = self.get(bucket).get(NOTIFICATION)
        if not raw:
            return None
        with self._lock:
            hit = self._notif_parsed.get(bucket)
            if hit is not None and hit[0] == raw:
                return hit[1]
        try:
            cfg = ncfg.NotificationConfig.from_xml(raw)
        except Exception:
            cfg = None
        with self._lock:
            self._notif_parsed[bucket] = (raw, cfg)
        return cfg
