"""Per-bucket metadata subsystems: policy, lifecycle, tagging, object
lock, quota, SSE config, notification, replication (reference
cmd/bucket-metadata-sys.go + internal/bucket/*)."""

from .metadata import BucketMetadataSys  # noqa: F401
