"""Bucket CORS configuration: parsing and request matching.

Reference: S3 CORSConfiguration semantics (the reference serves CORS for
the console via internal config; the S3-level config API and preflight
behavior follow AWS): rules with AllowedOrigin (wildcard-able),
AllowedMethod, AllowedHeader, ExposeHeader, MaxAgeSeconds; the first
rule matching (origin, method, requested headers) wins.
"""

from __future__ import annotations

import fnmatch
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

_ALLOWED_METHODS = {"GET", "PUT", "POST", "DELETE", "HEAD"}


class CORSError(ValueError):
    pass


@dataclass
class CORSRule:
    allowed_origins: list[str] = field(default_factory=list)
    allowed_methods: list[str] = field(default_factory=list)
    allowed_headers: list[str] = field(default_factory=list)
    expose_headers: list[str] = field(default_factory=list)
    max_age_seconds: int = 0

    def match_origin(self, origin: str) -> bool:
        return any(fnmatch.fnmatchcase(origin, pat)
                   for pat in self.allowed_origins)

    def match(self, origin: str, method: str,
              req_headers: list[str]) -> bool:
        if not self.match_origin(origin):
            return False
        if method.upper() not in self.allowed_methods:
            return False
        if req_headers:
            allowed = [h.lower() for h in self.allowed_headers]
            for h in req_headers:
                h = h.strip().lower()
                if not h:
                    continue
                if "*" not in allowed and not any(
                        fnmatch.fnmatchcase(h, a) for a in allowed):
                    return False
        return True


@dataclass
class CORSConfig:
    rules: list[CORSRule] = field(default_factory=list)

    def find(self, origin: str, method: str,
             req_headers: list[str] | None = None) -> CORSRule | None:
        for r in self.rules:
            if r.match(origin, method, req_headers or []):
                return r
        return None


def _texts(el, tag: str) -> list[str]:
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    return ([e.text or "" for e in el.findall(f"{ns}{tag}")]
            or [e.text or "" for e in el.findall(tag)])


def parse_cors_xml(body: bytes) -> CORSConfig:
    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        raise CORSError(f"malformed XML: {e}")
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    rule_els = root.findall(f"{ns}CORSRule") or root.findall("CORSRule")
    if not rule_els:
        raise CORSError("at least one CORSRule is required")
    if len(rule_els) > 100:
        raise CORSError("no more than 100 CORSRules allowed")
    cfg = CORSConfig()
    for el in rule_els:
        rule = CORSRule(
            allowed_origins=[o for o in _texts(el, "AllowedOrigin") if o],
            allowed_methods=[m.upper()
                             for m in _texts(el, "AllowedMethod") if m],
            allowed_headers=[h for h in _texts(el, "AllowedHeader") if h],
            expose_headers=[h for h in _texts(el, "ExposeHeader") if h],
        )
        ages = _texts(el, "MaxAgeSeconds")
        if ages and ages[0]:
            try:
                rule.max_age_seconds = int(ages[0])
            except ValueError:
                raise CORSError("MaxAgeSeconds must be an integer")
            if rule.max_age_seconds < 0:
                raise CORSError("MaxAgeSeconds must not be negative")
        if not rule.allowed_origins:
            raise CORSError("CORSRule requires an AllowedOrigin")
        if not rule.allowed_methods:
            raise CORSError("CORSRule requires an AllowedMethod")
        bad = set(rule.allowed_methods) - _ALLOWED_METHODS
        if bad:
            raise CORSError(
                f"unsupported AllowedMethod: {', '.join(sorted(bad))}")
        cfg.rules.append(rule)
    return cfg


def cors_headers(rule: CORSRule, origin: str,
                 preflight_method: str = "",
                 req_headers: list[str] | None = None) -> dict[str, str]:
    """Response headers for a matched rule (preflight gets the method/
    header echoes and max-age; actual responses get expose-headers)."""
    h = {
        "Access-Control-Allow-Origin":
            "*" if rule.allowed_origins == ["*"] else origin,
        "Vary": "Origin",
    }
    # NOTE: no Access-Control-Allow-Credentials — AWS S3 never emits it,
    # and echoing origins matched by wildcard patterns WITH credentials
    # would be the exact combination the CORS spec forbids
    if preflight_method:
        h["Access-Control-Allow-Methods"] = ", ".join(rule.allowed_methods)
        if req_headers:
            h["Access-Control-Allow-Headers"] = ", ".join(
                x.strip() for x in req_headers if x.strip())
        if rule.max_age_seconds:
            h["Access-Control-Max-Age"] = str(rule.max_age_seconds)
    if rule.expose_headers:
        h["Access-Control-Expose-Headers"] = ", ".join(rule.expose_headers)
    return h
