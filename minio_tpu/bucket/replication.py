"""Bucket replication configuration model.

Reference: internal/bucket/replication/{replication,rule,destination}.go.
Rules carry Status/Priority/Filter/Destination plus the MinIO extensions
(DeleteMarkerReplication, DeleteReplication, ExistingObjectReplication).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass, field

from .lifecycle import Filter, _find, _findall, _text


@dataclass
class ReplicationRule:
    rule_id: str = ""
    status: str = "Enabled"
    priority: int = 0
    filter: Filter = field(default_factory=Filter)
    destination_arn: str = ""      # arn:minio:replication::<id>:<bucket>
    delete_marker_replication: bool = True
    delete_replication: bool = True
    existing_objects: bool = False

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"

    @classmethod
    def from_xml(cls, el) -> "ReplicationRule":
        r = cls(rule_id=_text(el, "ID"),
                status=_text(el, "Status", "Enabled"),
                priority=int(_text(el, "Priority", "0") or 0))
        fil = _find(el, "Filter")
        r.filter = Filter.from_xml(fil) if fil is not None else Filter(
            prefix=_text(el, "Prefix"))
        dst = _find(el, "Destination")
        if dst is not None:
            r.destination_arn = _text(dst, "Bucket")
        dmr = _find(el, "DeleteMarkerReplication")
        if dmr is not None:
            r.delete_marker_replication = _text(dmr, "Status") != "Disabled"
        dr = _find(el, "DeleteReplication")
        if dr is not None:
            r.delete_replication = _text(dr, "Status") != "Disabled"
        eo = _find(el, "ExistingObjectReplication")
        if eo is not None:
            r.existing_objects = _text(eo, "Status") == "Enabled"
        return r

    @property
    def target_bucket(self) -> str:
        # "arn:aws:s3:::bkt" or "arn:minio:replication::id:bkt" or plain name
        arn = self.destination_arn
        if arn.startswith("arn:"):
            return arn.rsplit(":", 1)[-1]
        return arn


class ReplicationConfig:
    def __init__(self, rules: list[ReplicationRule], role: str = ""):
        self.rules = sorted(rules, key=lambda r: -r.priority)
        self.role = role

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "ReplicationConfig":
        root = ET.fromstring(raw)
        rules = [ReplicationRule.from_xml(el) for el in _findall(root, "Rule")]
        if not rules:
            raise ValueError("replication config with no rules")
        return cls(rules, role=_text(root, "Role"))

    def match(self, name: str, tags: dict | None = None) -> ReplicationRule | None:
        """Highest-priority enabled rule matching the object."""
        for r in self.rules:
            if r.enabled and r.filter.matches(name, tags):
                return r
        return None

    def replicate_deletes(self, name: str) -> bool:
        r = self.match(name)
        return bool(r and r.delete_replication)

    def replicate_delete_markers(self, name: str) -> bool:
        r = self.match(name)
        return bool(r and r.delete_marker_replication)
