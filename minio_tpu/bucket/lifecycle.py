"""Bucket lifecycle (ILM) configuration model and evaluation.

Reference: internal/bucket/lifecycle/lifecycle.go (rule matching +
`ComputeAction`), internal/bucket/lifecycle/rule.go (XML schema).
Supports Expiration (Days/Date/ExpiredObjectDeleteMarker),
NoncurrentVersionExpiration, Transition / NoncurrentVersionTransition
(StorageClass = tier name), AbortIncompleteMultipartUpload, and
Prefix/Tag/And filters.  The data scanner evaluates every scanned version
against `compute_action` (reference cmd/data-scanner.go:891).
"""

from __future__ import annotations

import time
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from enum import Enum

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _find(el, tag):
    e = el.find(f"{_NS}{tag}")
    if e is None:
        e = el.find(tag)
    return e


def _findall(el, tag):
    return el.findall(f"{_NS}{tag}") or el.findall(tag)


def _text(el, tag, default=""):
    e = _find(el, tag)
    return (e.text or default) if e is not None else default


class Action(Enum):
    NONE = "none"
    DELETE = "delete"                       # expire latest version
    DELETE_VERSION = "delete-version"       # expire noncurrent version
    DELETE_MARKER = "delete-marker"         # remove expired delete marker
    TRANSITION = "transition"
    TRANSITION_VERSION = "transition-version"
    ABORT_MULTIPART = "abort-multipart"


DAY = 24 * 3600.0


@dataclass
class Filter:
    prefix: str = ""
    tags: dict = field(default_factory=dict)

    @classmethod
    def from_xml(cls, el) -> "Filter":
        f = cls()
        if el is None:
            return f
        and_el = _find(el, "And")
        scope = and_el if and_el is not None else el
        f.prefix = _text(scope, "Prefix")
        for tag_el in _findall(scope, "Tag"):
            k = _text(tag_el, "Key")
            if k:
                f.tags[k] = _text(tag_el, "Value")
        return f

    def matches(self, name: str, obj_tags: dict | None) -> bool:
        if self.prefix and not name.startswith(self.prefix):
            return False
        if self.tags:
            obj_tags = obj_tags or {}
            for k, v in self.tags.items():
                if obj_tags.get(k) != v:
                    return False
        return True


@dataclass
class Rule:
    rule_id: str = ""
    status: str = "Enabled"
    filter: Filter = field(default_factory=Filter)
    expiration_days: int = 0
    expiration_date: float = 0.0
    expire_delete_marker: bool = False
    noncurrent_days: int = 0
    newer_noncurrent_versions: int = 0
    transition_days: int = -1
    transition_date: float = 0.0
    transition_tier: str = ""
    nc_transition_days: int = -1
    nc_transition_tier: str = ""
    abort_mpu_days: int = 0

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"

    @classmethod
    def from_xml(cls, el) -> "Rule":
        r = cls(rule_id=_text(el, "ID"), status=_text(el, "Status", "Enabled"))
        fil = _find(el, "Filter")
        if fil is not None:
            r.filter = Filter.from_xml(fil)
        else:
            # legacy top-level <Prefix>
            r.filter = Filter(prefix=_text(el, "Prefix"))
        exp = _find(el, "Expiration")
        if exp is not None:
            r.expiration_days = int(_text(exp, "Days", "0") or 0)
            d = _text(exp, "Date")
            if d:
                r.expiration_date = _parse_date(d)
            r.expire_delete_marker = (
                _text(exp, "ExpiredObjectDeleteMarker").lower() == "true"
            )
        nce = _find(el, "NoncurrentVersionExpiration")
        if nce is not None:
            r.noncurrent_days = int(_text(nce, "NoncurrentDays", "0") or 0)
            r.newer_noncurrent_versions = int(
                _text(nce, "NewerNoncurrentVersions", "0") or 0
            )
        tr = _find(el, "Transition")
        if tr is not None:
            r.transition_days = int(_text(tr, "Days", "0") or 0)
            d = _text(tr, "Date")
            if d:
                r.transition_date = _parse_date(d)
            r.transition_tier = _text(tr, "StorageClass")
        nct = _find(el, "NoncurrentVersionTransition")
        if nct is not None:
            r.nc_transition_days = int(_text(nct, "NoncurrentDays", "0") or 0)
            r.nc_transition_tier = _text(nct, "StorageClass")
        ab = _find(el, "AbortIncompleteMultipartUpload")
        if ab is not None:
            r.abort_mpu_days = int(_text(ab, "DaysAfterInitiation", "0") or 0)
        return r


def _parse_date(s: str) -> float:
    s = s.strip().rstrip("Z")
    try:
        return time.mktime(time.strptime(s[:10], "%Y-%m-%d"))
    except ValueError:
        return 0.0


@dataclass
class ObjectOpts:
    """Evaluation input (reference lifecycle.ObjectOpts)."""

    name: str
    mod_time: float = 0.0
    is_latest: bool = True
    delete_marker: bool = False
    num_versions: int = 1
    successor_mod_time: float = 0.0   # for noncurrent: when superseded
    tags: dict | None = None
    transition_status: str = ""       # "complete" once tiered


@dataclass
class Event:
    action: Action = Action.NONE
    tier: str = ""
    rule_id: str = ""
    due: float = 0.0


class Lifecycle:
    def __init__(self, rules: list[Rule]):
        self.rules = rules

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "Lifecycle":
        root = ET.fromstring(raw)
        rules = [Rule.from_xml(el) for el in _findall(root, "Rule")]
        if not rules:
            raise ValueError("lifecycle config with no rules")
        if len(rules) > 1000:
            raise ValueError("too many lifecycle rules")
        return cls(rules)

    def compute_action(self, obj: ObjectOpts, now: float | None = None) -> Event:
        """Pick the applicable action for one object version
        (reference lifecycle.Lifecycle.ComputeAction / Eval)."""
        now = time.time() if now is None else now
        ev = Event()
        for rule in self.rules:
            if not rule.enabled or not rule.filter.matches(obj.name, obj.tags):
                continue

            if not obj.is_latest:
                # noncurrent expiration / transition
                base = obj.successor_mod_time or obj.mod_time
                if rule.noncurrent_days and base:
                    due = base + rule.noncurrent_days * DAY
                    if now >= due:
                        ev = _pick(ev, Event(Action.DELETE_VERSION,
                                             rule_id=rule.rule_id, due=due))
                if (rule.nc_transition_days >= 0 and rule.nc_transition_tier
                        and not obj.transition_status and base):
                    due = base + rule.nc_transition_days * DAY
                    if now >= due:
                        ev = _pick(ev, Event(Action.TRANSITION_VERSION,
                                             tier=rule.nc_transition_tier,
                                             rule_id=rule.rule_id, due=due))
                continue

            if obj.delete_marker:
                # a delete marker with no other versions left is "expired"
                if rule.expire_delete_marker and obj.num_versions == 1:
                    ev = _pick(ev, Event(Action.DELETE_MARKER,
                                         rule_id=rule.rule_id, due=now))
                continue

            if rule.expiration_days and obj.mod_time:
                due = obj.mod_time + rule.expiration_days * DAY
                if now >= due:
                    ev = _pick(ev, Event(Action.DELETE,
                                         rule_id=rule.rule_id, due=due))
            if rule.expiration_date and now >= rule.expiration_date:
                ev = _pick(ev, Event(Action.DELETE, rule_id=rule.rule_id,
                                     due=rule.expiration_date))
            if (rule.transition_tier and not obj.transition_status
                    and obj.mod_time):
                due = (rule.transition_date
                       or obj.mod_time + max(rule.transition_days, 0) * DAY)
                if rule.transition_days >= 0 and now >= due:
                    ev = _pick(ev, Event(Action.TRANSITION,
                                         tier=rule.transition_tier,
                                         rule_id=rule.rule_id, due=due))
        return ev

    def abort_multipart_days(self, name: str) -> int:
        """Smallest DaysAfterInitiation among matching rules (0 = none)."""
        days = 0
        for rule in self.rules:
            if not rule.enabled or not rule.filter.matches(name, None):
                continue
            if rule.abort_mpu_days and (not days or rule.abort_mpu_days < days):
                days = rule.abort_mpu_days
        return days


def _pick(cur: Event, new: Event) -> Event:
    """Deletion beats transition; earlier due date wins within a class
    (reference lifecycle.go Eval ordering)."""
    if cur.action == Action.NONE:
        return new
    cur_del = cur.action in (Action.DELETE, Action.DELETE_VERSION,
                             Action.DELETE_MARKER)
    new_del = new.action in (Action.DELETE, Action.DELETE_VERSION,
                             Action.DELETE_MARKER)
    if new_del and not cur_del:
        return new
    if cur_del and not new_del:
        return cur
    return new if new.due < cur.due else cur
