"""Production traffic simulator (ISSUE 15 tentpole, half 2).

Declarative scenarios replayed against the REAL HTTP server with
seeded-deterministic arrival schedules; each scenario asserts its SLOs
through the server's own SLO plane (``GET /minio/admin/v3/slo``) and a
violated scenario pulls the retained trace store to attribute the
violation to the dominant span stage.  ``python bench.py sim`` drives
the builtin scenario set and writes the SIM_r01.json regression
surface.
"""

from .engine import ScenarioEngine, build_schedule, schedule_digest
from .scenarios import (Scenario, builtin_scenarios,
                        controller_scenarios, georep_scenarios)

__all__ = ["Scenario", "ScenarioEngine", "build_schedule",
           "builtin_scenarios", "controller_scenarios",
           "georep_scenarios", "schedule_digest"]
