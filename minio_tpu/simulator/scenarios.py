"""Declarative traffic scenarios (ISSUE 15).

A :class:`Scenario` is pure data: everything the engine needs to build
a deterministic arrival schedule (see ``engine.build_schedule`` — same
seed, same schedule, same request sequence) plus the SLOs the scenario
asserts after replay and the chaos hook it arms mid-run.

``builtin_scenarios()`` is the production mix ``python bench.py sim``
replays: zipf read fan-in, multipart ingest storm, list-heavy
analytics, a multi-tenant QoS mix, and two chaos variants (flaky-drive
brownout, pool drain under live traffic — the PR 14 harness shape).
Scenario SLO grammar::

    slo = {
      "classes": {"GET": {"p99_ms": 400, "availability": 0.995}},
      "shed_fraction_max": 0.05,          # client-side 503 fraction
      "buckets": {"simquiet": {"p99_ms": 800, "p50_ms": 200,
                               "shed_max": 0, "shed_frac_max": 0.1}},
    }

``classes`` asserts against the server's own accounting (the admin SLO
endpoint, windowed to the scenario); ``buckets`` asserts client-side
per-bucket latencies (the noisy-neighbor clause of the QoS mix).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Scenario:
    name: str
    seed: int
    duration_s: float
    clients: int
    rate: float                      # aggregate Poisson arrival rate, req/s
    ops: tuple                       # ((op, weight), ...); ops: get|head|
    #                                  put|list|delete|mpu
    buckets: tuple = ("sim",)
    #: fraction of requests aimed at buckets[0]; None = uniform.  The
    #: QoS mix points most traffic at the hot bucket/tenant.
    hot_bucket_frac: float | None = None
    nobjects: int = 48               # catalog keys per bucket (setup PUTs)
    obj_bytes: tuple = (4 << 10, 64 << 10)
    zipf_s: float = 1.1              # GET popularity skew
    put_bytes: tuple = (8 << 10, 96 << 10)
    mpu_parts: int = 2               # parts per multipart upload
    mpu_part_bytes: int = 5 << 20    # all-but-last part size (S3 minimum)
    mpu_last_bytes: int = 64 << 10
    list_max_keys: int = 100
    slo: dict = field(default_factory=dict)
    #: deterministic REGIME SHIFTS (ISSUE 18): piecewise arrival-rate
    #: multipliers ((start_frac, end_frac, mult), ...) applied inside
    #: build_schedule's Poisson loop — still a pure function of the
    #: scenario, so the schedule digest pins the shifted shape too
    rate_profile: tuple = ()
    #: tenant-mix flip: from this fraction of the run on, the hot role
    #: (hot_bucket_frac) moves from buckets[0] to buckets[1]; the
    #: displaced bucket joins the quiet set.  None = no flip.
    mix_flip_at_frac: float | None = None
    #: per-bucket op-mix override ``{bucket: ((op, weight), ...)}`` —
    #: tenants with different WORKLOADS (a PUT-flood offender vs a
    #: GET-only victim).  Buckets absent here draw from ``ops``.
    #: Gated: scenarios without it keep their exact RNG stream.
    bucket_ops: dict | None = None
    #: role swap riding ``mix_flip_at_frac``: from the flip on,
    #: buckets named here draw THIS mix instead of their
    #: ``bucket_ops`` one — the flood itself moves tenants, not just
    #: the arrival share.  Gated the same way.
    bucket_ops_post_flip: dict | None = None
    #: dedicated client pools ``{bucket: (first_client, n_clients)}``:
    #: that bucket's entries replay on their own closed-loop client
    #: span.  Without this, a stalled offender throttles the victim's
    #: OFFERED load too (every client serves every bucket, and a
    #: closed loop equalizes), hiding the very starvation a scenario
    #: wants to grade.  Buckets absent here keep the global
    #: ``i % clients`` assignment.  Gated: no extra RNG draws.
    bucket_clients: dict | None = None
    chaos: str | None = None         # engine chaos-hook name
    chaos_at_frac: float = 0.25      # hook start, fraction of duration
    chaos_dur_frac: float = 0.5      # hook length, fraction of duration
    qos: dict | None = None          # admin qos doc applied for the run
    description: str = ""


def builtin_scenarios(scale: float = 1.0) -> list[Scenario]:
    """The ``bench.py sim`` set.  ``scale`` multiplies durations
    (rates are part of each scenario's identity and stay fixed) so a
    short tier can exercise the same shapes in less wall time; seeds
    are fixed — the schedule digests in SIM_r01.json are the
    reproducibility pin."""
    d = lambda s: max(3.0, s * scale)  # noqa: E731

    return [
        Scenario(
            name="zipf_read_fanin", seed=1501, duration_s=d(12),
            clients=8, rate=160.0, ops=(("get", 92), ("head", 8)),
            nobjects=64, zipf_s=1.1,
            slo={"classes": {
                "GET": {"p99_ms": 900.0, "availability": 0.999}},
                "shed_fraction_max": 0.01},
            description="million-user CDN shape: zipf(1.1) GET/HEAD "
                        "fan-in over a small hot set, served from the "
                        "hot tier"),
        Scenario(
            name="multipart_ingest_storm", seed=1502, duration_s=d(12),
            clients=6, rate=14.0,
            ops=(("mpu", 3), ("put", 9), ("get", 4)),
            nobjects=16, mpu_parts=2,
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "MULTIPART": {"p99_ms": 9000.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="bulk ingest: multipart uploads (5MiB parts) "
                        "racing single PUTs and readbacks"),
        Scenario(
            name="list_heavy_analytics", seed=1503, duration_s=d(10),
            clients=6, rate=60.0,
            ops=(("list", 55), ("get", 35), ("head", 10)),
            nobjects=96,
            slo={"classes": {
                "LIST": {"p99_ms": 1500.0, "availability": 0.999},
                "GET": {"p99_ms": 1200.0, "availability": 0.999}},
                "shed_fraction_max": 0.02},
            description="analytics shape: namespace walks dominating, "
                        "point reads riding along"),
        Scenario(
            name="multi_tenant_qos_mix", seed=1504, duration_s=d(12),
            clients=10, rate=120.0,
            ops=(("get", 80), ("put", 15), ("list", 5)),
            buckets=("simhot", "simquiet"), hot_bucket_frac=0.9,
            nobjects=32,
            qos={"enable": True, "max_queue": 64, "tenants": {
                "bucket:simhot": {"weight": 1, "max_concurrency": 2},
                "bucket:simquiet": {"weight": 8}}},
            slo={"buckets": {
                "simquiet": {"p99_ms": 2500.0, "shed_max": 0}},
                # the hot tenant IS expected to shed under its cap;
                # only runaway collapse fails the scenario
                "shed_fraction_max": 0.75},
            description="noisy neighbor: 90% of arrivals hammer the "
                        "capped hot tenant; the quiet tenant must not "
                        "feel it (weighted DRR isolation)"),
        Scenario(
            name="chaos_disk_brownout", seed=1505, duration_s=d(14),
            clients=8, rate=80.0, ops=(("get", 90), ("put", 10)),
            nobjects=48, chaos="disk",
            chaos_at_frac=0.25, chaos_dur_frac=0.4,
            slo={"classes": {
                "GET": {"p99_ms": 2500.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="two drives turn slow+flaky mid-run "
                        "(ChaosDisk); hedged reads + the breaker must "
                        "hold availability inside parity"),
        # MUST stay last: its drain decommissions pool 1 of bench_sim's
        # shared server for good (bench_sim asserts this ordering)
        Scenario(
            name="drain_under_traffic", seed=1506, duration_s=d(14),
            clients=8, rate=70.0, ops=(("get", 85), ("put", 15)),
            nobjects=48, chaos="drain",
            chaos_at_frac=0.2, chaos_dur_frac=1.0,
            slo={"classes": {
                "GET": {"p99_ms": 2500.0, "availability": 0.995},
                "PUT": {"p99_ms": 5000.0, "availability": 0.99}},
                "shed_fraction_max": 0.05},
            description="PR 14 harness shape: a pool decommission "
                        "starts mid-traffic; reads stay findable "
                        "mid-move, writes route to live pools"),
    ]


def controller_scenarios(scale: float = 1.0) -> list[Scenario]:
    """The regime-shift family (ISSUE 18): each scenario is replayed
    TWICE by ``bench.py controller`` — once with the static config only
    (``MINIO_TPU_CONTROLLER=0``) and once with the overload controller
    on — against a deliberately scarce server (4 admission slots,
    600ms request deadline, hot cache off, a ~40ms floor on every
    drive op) so saturation is a property of the schedule, not of box
    noise.

    The starvation mechanism is SLOT-TIME, not grant share.  The DRR
    admission sweep is grant-fair: every backlogged tenant is visited
    each round, so a cost-1 victim cannot lose the weight game — but
    grants are not seconds.  A PUT costs ~10 serialized drive ops
    (xl.meta + shards + dirs) against a GET's ~2, so a PUT-flood
    tenant holds an admission slot ~4x longer per grant, the pool's
    RELEASE RATE collapses, and a GET victim whose demand exceeds
    release_rate/#backlogged starves into 600ms-deadline sheds — with
    the static config's weights (offender 16, victim 1) doing nothing
    to stop it.  The controller's rescue is the one actuator that
    prices slot-TIME: the offender's max_concurrency rung bounds how
    many slots its slow PUTs may occupy, restoring the release rate
    for everyone else.  The flooding tenant is EXPECTED to shed (its
    demand exceeds capacity by design; under the controller its own
    queue backs up even further), so the aggregate shed budgets are
    deliberately loose — victim isolation, not total shed volume, is
    what is being graded.

    Every scenario partitions its clients (``bucket_clients``): the
    victim drives the server from its OWN closed-loop pool.  With a
    shared pool a client stalled on a flooded request stops issuing
    victim requests too, the victim's offered load collapses in
    lockstep with the overload, and the grant-fair sweep trivially
    drains the shrunken victim backlog — the closed loop itself would
    hide the starvation from the verdict."""
    d = lambda s: max(3.0, s * scale)  # noqa: E731
    victim_ops = (("get", 100),)
    flood_ops = (("put", 70), ("get", 30))
    return [
        Scenario(
            name="flash_crowd", seed=1801, duration_s=d(15),
            clients=26, rate=16.0,
            ops=(("get", 100),),
            buckets=("flashhot", "flashquiet"), hot_bucket_frac=0.7,
            bucket_ops={"flashhot": flood_ops,
                        "flashquiet": victim_ops},
            bucket_clients={"flashhot": (0, 18),
                            "flashquiet": (18, 8)},
            nobjects=16, obj_bytes=(4 << 10, 32 << 10),
            put_bytes=(64 << 10, 256 << 10),
            rate_profile=((0.3, 1.0, 3.0),),
            qos={"enable": True, "max_queue": 64, "tenants": {
                "bucket:flashhot": {"weight": 16},
                "bucket:flashquiet": {"weight": 1}}},
            slo={"buckets": {
                "flashquiet": {"shed_frac_max": 0.25, "p50_ms": 520.0}},
                "shed_fraction_max": 0.9},
            description="flash crowd: arrivals triple from 30% of the "
                        "run on; the PUT-flood tenant's slow writes "
                        "hold the 4 admission slots and the GET "
                        "tenant starves unless the offender is "
                        "conc-capped"),
        Scenario(
            name="tenant_mix_flip", seed=1802, duration_s=d(14),
            clients=26, rate=42.0,
            ops=(("get", 100),),
            buckets=("mixa", "mixb", "mixquiet"), hot_bucket_frac=0.55,
            bucket_ops={"mixa": flood_ops, "mixb": victim_ops,
                        "mixquiet": victim_ops},
            bucket_ops_post_flip={"mixa": victim_ops,
                                  "mixb": flood_ops},
            bucket_clients={"mixa": (0, 9), "mixb": (9, 9),
                            "mixquiet": (18, 8)},
            nobjects=16, obj_bytes=(4 << 10, 32 << 10),
            put_bytes=(64 << 10, 256 << 10),
            mix_flip_at_frac=0.5,
            qos={"enable": True, "max_queue": 64, "tenants": {
                "bucket:mixa": {"weight": 16},
                "bucket:mixb": {"weight": 16},
                "bucket:mixquiet": {"weight": 1}}},
            slo={"buckets": {
                "mixquiet": {"shed_frac_max": 0.3, "p50_ms": 500.0}},
                "shed_fraction_max": 0.9},
            description="tenant-mix flip: the PUT flood moves from "
                        "tenant A to tenant B mid-run; a static cap "
                        "on A is useless after the flip — the "
                        "controller must re-identify the offender and "
                        "retarget its cap in one reconfigure"),
        Scenario(
            name="brownout_noisy_stacked", seed=1803,
            duration_s=d(14), clients=26, rate=42.0,
            ops=(("get", 100),),
            buckets=("stackhot", "stackquiet"), hot_bucket_frac=0.7,
            bucket_ops={"stackhot": flood_ops,
                        "stackquiet": victim_ops},
            bucket_clients={"stackhot": (0, 18),
                            "stackquiet": (18, 8)},
            nobjects=16, obj_bytes=(4 << 10, 32 << 10),
            put_bytes=(64 << 10, 256 << 10),
            chaos="disk", chaos_at_frac=0.3, chaos_dur_frac=0.5,
            qos={"enable": True, "max_queue": 64, "tenants": {
                "bucket:stackhot": {"weight": 16},
                "bucket:stackquiet": {"weight": 1}}},
            slo={"buckets": {
                # shed is the discriminator here: the victim's p50
                # rides the chaos disk's added latency, which the
                # controller can route around (hedge) but not remove —
                # the p50 clause is a deadline bound, not the grade
                "stackquiet": {"shed_frac_max": 0.4, "p50_ms": 650.0}},
                "shed_fraction_max": 0.9},
            description="stacked faults: a PUT flood saturates "
                        "admission while one drive turns slow+flaky "
                        "mid-run; the controller stacks the QoS cap, "
                        "wider read hedging, and a forced background "
                        "brownout"),
    ]


def georep_scenarios(scale: float = 1.0) -> list[Scenario]:
    """The multi-region family (ISSUE 16): replayed against the
    PRIMARY of a two-cluster pair with ``MINIO_TPU_GEOREP=1`` and a
    joined site peer.  The engine grades the primary-facing SLO (the
    whole point of the async push queue is that the client never waits
    on the WAN); cross-site convergence and read-your-writes are graded
    AFTER replay by the harness polling the secondary for byte-identity
    (``bench.py sim`` records both next to the scenario verdicts).

    Each scenario owns its bucket so convergence checks can't bleed
    across scenarios.  Chaos hooks the harness must register:

    * ``peer_kill`` — close the secondary mid-push, restart it at the
      SAME port (the breaker must open, then the retried sweeps must
      converge against the restarted peer);
    * ``worker_kill`` — SIGKILL one mp I/O worker of the primary
      (``MINIO_TPU_WORKERS>=1``); the plane supervisor respawns it and
      in-flight PUTs surface as honest errors inside the availability
      budget.
    """
    d = lambda s: max(3.0, s * scale)  # noqa: E731

    return [
        Scenario(
            name="replication_burst", seed=1601, duration_s=d(10),
            clients=6, rate=50.0,
            ops=(("put", 55), ("get", 38), ("delete", 7)),
            buckets=("grburst",), nobjects=32,
            put_bytes=(8 << 10, 64 << 10),
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "GET": {"p99_ms": 1500.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="write burst while the push queue drains to "
                        "the peer: primary-facing PUT latency must not "
                        "absorb the WAN (async replication), deletes "
                        "replicate as versioned markers"),
        Scenario(
            name="peer_kill_mid_push", seed=1602, duration_s=d(12),
            clients=6, rate=45.0,
            ops=(("put", 50), ("get", 50)),
            buckets=("grpeer",), nobjects=32,
            chaos="peer_kill", chaos_at_frac=0.25, chaos_dur_frac=0.4,
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "GET": {"p99_ms": 1500.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="secondary killed mid-push and restarted at "
                        "the same address: breaker opens, primary SLO "
                        "holds, retried sweeps converge after restart"),
        Scenario(
            name="worker_kill", seed=1603, duration_s=d(12),
            clients=6, rate=45.0,
            ops=(("put", 45), ("get", 55)),
            buckets=("grwork",), nobjects=32,
            # PUT bodies must clear the 128 KiB inline bound: inline
            # objects never reach the mp worker plane, and a kill that
            # can't hit an in-flight job tests nothing
            put_bytes=(160 << 10, 256 << 10),
            chaos="worker_kill", chaos_at_frac=0.3, chaos_dur_frac=0.3,
            # the PUT budget PRICES the designed fault: a SIGKILL
            # deterministically fails the in-flight jobs of the dead
            # worker until the supervisor respawns it (~2-3% of this
            # schedule's PUTs on the shared container); 0.95 passes
            # that baseline while still failing a supervisor that
            # cannot keep workers alive
            slo={"classes": {
                "PUT": {"p99_ms": 5000.0, "availability": 0.95},
                "GET": {"p99_ms": 2000.0, "availability": 0.99}},
                "shed_fraction_max": 0.05},
            description="one mp I/O worker of the primary SIGKILLed "
                        "mid-run; the plane supervisor respawns it, "
                        "the kill window's in-flight PUTs fit the "
                        "availability budget, replication still "
                        "converges"),
        Scenario(
            name="read_your_writes_across_sites", seed=1604,
            duration_s=d(10), clients=4, rate=30.0,
            ops=(("put", 60), ("get", 40)),
            buckets=("grryw",), nobjects=24,
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "GET": {"p99_ms": 1500.0, "availability": 0.995}},
                "shed_fraction_max": 0.02},
            description="every acknowledged write must become readable "
                        "BYTE-IDENTICAL on the secondary: the harness "
                        "polls the peer after replay and records the "
                        "convergence lag next to this verdict"),
    ]


def smoke_scenario() -> Scenario:
    """Tier-1 sized: a few seconds against a real server, generous
    budgets (CI boxes are noisy — this pins the loop closes, not that
    CI is fast)."""
    return Scenario(
        name="smoke_zipf_read", seed=7701, duration_s=3.0, clients=4,
        rate=40.0, ops=(("get", 80), ("put", 12), ("list", 8)),
        nobjects=12, obj_bytes=(2 << 10, 8 << 10),
        put_bytes=(2 << 10, 8 << 10),
        slo={"classes": {
            "GET": {"p99_ms": 15000.0, "availability": 0.98}},
            "shed_fraction_max": 0.2},
        description="tier-1 smoke: tiny zipf mix, generous budgets")
