"""Declarative traffic scenarios (ISSUE 15).

A :class:`Scenario` is pure data: everything the engine needs to build
a deterministic arrival schedule (see ``engine.build_schedule`` — same
seed, same schedule, same request sequence) plus the SLOs the scenario
asserts after replay and the chaos hook it arms mid-run.

``builtin_scenarios()`` is the production mix ``python bench.py sim``
replays: zipf read fan-in, multipart ingest storm, list-heavy
analytics, a multi-tenant QoS mix, and two chaos variants (flaky-drive
brownout, pool drain under live traffic — the PR 14 harness shape).
Scenario SLO grammar::

    slo = {
      "classes": {"GET": {"p99_ms": 400, "availability": 0.995}},
      "shed_fraction_max": 0.05,          # client-side 503 fraction
      "buckets": {"simquiet": {"p99_ms": 800, "shed_max": 0}},
    }

``classes`` asserts against the server's own accounting (the admin SLO
endpoint, windowed to the scenario); ``buckets`` asserts client-side
per-bucket latencies (the noisy-neighbor clause of the QoS mix).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Scenario:
    name: str
    seed: int
    duration_s: float
    clients: int
    rate: float                      # aggregate Poisson arrival rate, req/s
    ops: tuple                       # ((op, weight), ...); ops: get|head|
    #                                  put|list|delete|mpu
    buckets: tuple = ("sim",)
    #: fraction of requests aimed at buckets[0]; None = uniform.  The
    #: QoS mix points most traffic at the hot bucket/tenant.
    hot_bucket_frac: float | None = None
    nobjects: int = 48               # catalog keys per bucket (setup PUTs)
    obj_bytes: tuple = (4 << 10, 64 << 10)
    zipf_s: float = 1.1              # GET popularity skew
    put_bytes: tuple = (8 << 10, 96 << 10)
    mpu_parts: int = 2               # parts per multipart upload
    mpu_part_bytes: int = 5 << 20    # all-but-last part size (S3 minimum)
    mpu_last_bytes: int = 64 << 10
    list_max_keys: int = 100
    slo: dict = field(default_factory=dict)
    chaos: str | None = None         # engine chaos-hook name
    chaos_at_frac: float = 0.25      # hook start, fraction of duration
    chaos_dur_frac: float = 0.5      # hook length, fraction of duration
    qos: dict | None = None          # admin qos doc applied for the run
    description: str = ""


def builtin_scenarios(scale: float = 1.0) -> list[Scenario]:
    """The ``bench.py sim`` set.  ``scale`` multiplies durations
    (rates are part of each scenario's identity and stay fixed) so a
    short tier can exercise the same shapes in less wall time; seeds
    are fixed — the schedule digests in SIM_r01.json are the
    reproducibility pin."""
    d = lambda s: max(3.0, s * scale)  # noqa: E731

    return [
        Scenario(
            name="zipf_read_fanin", seed=1501, duration_s=d(12),
            clients=8, rate=160.0, ops=(("get", 92), ("head", 8)),
            nobjects=64, zipf_s=1.1,
            slo={"classes": {
                "GET": {"p99_ms": 900.0, "availability": 0.999}},
                "shed_fraction_max": 0.01},
            description="million-user CDN shape: zipf(1.1) GET/HEAD "
                        "fan-in over a small hot set, served from the "
                        "hot tier"),
        Scenario(
            name="multipart_ingest_storm", seed=1502, duration_s=d(12),
            clients=6, rate=14.0,
            ops=(("mpu", 3), ("put", 9), ("get", 4)),
            nobjects=16, mpu_parts=2,
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "MULTIPART": {"p99_ms": 9000.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="bulk ingest: multipart uploads (5MiB parts) "
                        "racing single PUTs and readbacks"),
        Scenario(
            name="list_heavy_analytics", seed=1503, duration_s=d(10),
            clients=6, rate=60.0,
            ops=(("list", 55), ("get", 35), ("head", 10)),
            nobjects=96,
            slo={"classes": {
                "LIST": {"p99_ms": 1500.0, "availability": 0.999},
                "GET": {"p99_ms": 1200.0, "availability": 0.999}},
                "shed_fraction_max": 0.02},
            description="analytics shape: namespace walks dominating, "
                        "point reads riding along"),
        Scenario(
            name="multi_tenant_qos_mix", seed=1504, duration_s=d(12),
            clients=10, rate=120.0,
            ops=(("get", 80), ("put", 15), ("list", 5)),
            buckets=("simhot", "simquiet"), hot_bucket_frac=0.9,
            nobjects=32,
            qos={"enable": True, "max_queue": 64, "tenants": {
                "bucket:simhot": {"weight": 1, "max_concurrency": 2},
                "bucket:simquiet": {"weight": 8}}},
            slo={"buckets": {
                "simquiet": {"p99_ms": 2500.0, "shed_max": 0}},
                # the hot tenant IS expected to shed under its cap;
                # only runaway collapse fails the scenario
                "shed_fraction_max": 0.75},
            description="noisy neighbor: 90% of arrivals hammer the "
                        "capped hot tenant; the quiet tenant must not "
                        "feel it (weighted DRR isolation)"),
        Scenario(
            name="chaos_disk_brownout", seed=1505, duration_s=d(14),
            clients=8, rate=80.0, ops=(("get", 90), ("put", 10)),
            nobjects=48, chaos="disk",
            chaos_at_frac=0.25, chaos_dur_frac=0.4,
            slo={"classes": {
                "GET": {"p99_ms": 2500.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="two drives turn slow+flaky mid-run "
                        "(ChaosDisk); hedged reads + the breaker must "
                        "hold availability inside parity"),
        # MUST stay last: its drain decommissions pool 1 of bench_sim's
        # shared server for good (bench_sim asserts this ordering)
        Scenario(
            name="drain_under_traffic", seed=1506, duration_s=d(14),
            clients=8, rate=70.0, ops=(("get", 85), ("put", 15)),
            nobjects=48, chaos="drain",
            chaos_at_frac=0.2, chaos_dur_frac=1.0,
            slo={"classes": {
                "GET": {"p99_ms": 2500.0, "availability": 0.995},
                "PUT": {"p99_ms": 5000.0, "availability": 0.99}},
                "shed_fraction_max": 0.05},
            description="PR 14 harness shape: a pool decommission "
                        "starts mid-traffic; reads stay findable "
                        "mid-move, writes route to live pools"),
    ]


def georep_scenarios(scale: float = 1.0) -> list[Scenario]:
    """The multi-region family (ISSUE 16): replayed against the
    PRIMARY of a two-cluster pair with ``MINIO_TPU_GEOREP=1`` and a
    joined site peer.  The engine grades the primary-facing SLO (the
    whole point of the async push queue is that the client never waits
    on the WAN); cross-site convergence and read-your-writes are graded
    AFTER replay by the harness polling the secondary for byte-identity
    (``bench.py sim`` records both next to the scenario verdicts).

    Each scenario owns its bucket so convergence checks can't bleed
    across scenarios.  Chaos hooks the harness must register:

    * ``peer_kill`` — close the secondary mid-push, restart it at the
      SAME port (the breaker must open, then the retried sweeps must
      converge against the restarted peer);
    * ``worker_kill`` — SIGKILL one mp I/O worker of the primary
      (``MINIO_TPU_WORKERS>=1``); the plane supervisor respawns it and
      in-flight PUTs surface as honest errors inside the availability
      budget.
    """
    d = lambda s: max(3.0, s * scale)  # noqa: E731

    return [
        Scenario(
            name="replication_burst", seed=1601, duration_s=d(10),
            clients=6, rate=50.0,
            ops=(("put", 55), ("get", 38), ("delete", 7)),
            buckets=("grburst",), nobjects=32,
            put_bytes=(8 << 10, 64 << 10),
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "GET": {"p99_ms": 1500.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="write burst while the push queue drains to "
                        "the peer: primary-facing PUT latency must not "
                        "absorb the WAN (async replication), deletes "
                        "replicate as versioned markers"),
        Scenario(
            name="peer_kill_mid_push", seed=1602, duration_s=d(12),
            clients=6, rate=45.0,
            ops=(("put", 50), ("get", 50)),
            buckets=("grpeer",), nobjects=32,
            chaos="peer_kill", chaos_at_frac=0.25, chaos_dur_frac=0.4,
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "GET": {"p99_ms": 1500.0, "availability": 0.995}},
                "shed_fraction_max": 0.05},
            description="secondary killed mid-push and restarted at "
                        "the same address: breaker opens, primary SLO "
                        "holds, retried sweeps converge after restart"),
        Scenario(
            name="worker_kill", seed=1603, duration_s=d(12),
            clients=6, rate=45.0,
            ops=(("put", 45), ("get", 55)),
            buckets=("grwork",), nobjects=32,
            # PUT bodies must clear the 128 KiB inline bound: inline
            # objects never reach the mp worker plane, and a kill that
            # can't hit an in-flight job tests nothing
            put_bytes=(160 << 10, 256 << 10),
            chaos="worker_kill", chaos_at_frac=0.3, chaos_dur_frac=0.3,
            # the PUT budget PRICES the designed fault: a SIGKILL
            # deterministically fails the in-flight jobs of the dead
            # worker until the supervisor respawns it (~2-3% of this
            # schedule's PUTs on the shared container); 0.95 passes
            # that baseline while still failing a supervisor that
            # cannot keep workers alive
            slo={"classes": {
                "PUT": {"p99_ms": 5000.0, "availability": 0.95},
                "GET": {"p99_ms": 2000.0, "availability": 0.99}},
                "shed_fraction_max": 0.05},
            description="one mp I/O worker of the primary SIGKILLed "
                        "mid-run; the plane supervisor respawns it, "
                        "the kill window's in-flight PUTs fit the "
                        "availability budget, replication still "
                        "converges"),
        Scenario(
            name="read_your_writes_across_sites", seed=1604,
            duration_s=d(10), clients=4, rate=30.0,
            ops=(("put", 60), ("get", 40)),
            buckets=("grryw",), nobjects=24,
            slo={"classes": {
                "PUT": {"p99_ms": 4000.0, "availability": 0.995},
                "GET": {"p99_ms": 1500.0, "availability": 0.995}},
                "shed_fraction_max": 0.02},
            description="every acknowledged write must become readable "
                        "BYTE-IDENTICAL on the secondary: the harness "
                        "polls the peer after replay and records the "
                        "convergence lag next to this verdict"),
    ]


def smoke_scenario() -> Scenario:
    """Tier-1 sized: a few seconds against a real server, generous
    budgets (CI boxes are noisy — this pins the loop closes, not that
    CI is fast)."""
    return Scenario(
        name="smoke_zipf_read", seed=7701, duration_s=3.0, clients=4,
        rate=40.0, ops=(("get", 80), ("put", 12), ("list", 8)),
        nobjects=12, obj_bytes=(2 << 10, 8 << 10),
        put_bytes=(2 << 10, 8 << 10),
        slo={"classes": {
            "GET": {"p99_ms": 15000.0, "availability": 0.98}},
            "shed_fraction_max": 0.2},
        description="tier-1 smoke: tiny zipf mix, generous budgets")
