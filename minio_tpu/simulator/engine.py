"""Scenario replay engine (ISSUE 15).

``build_schedule`` turns a :class:`Scenario` into a deterministic
arrival schedule — a pure function of the scenario (seeded
``random.Random``, no wall clock): same seed, same Poisson arrival
times, same op/key/size sequence.  ``schedule_digest`` pins that
(SIM_r01.json records it; a re-run must reproduce it bit-exact).

:class:`ScenarioEngine` replays a schedule against a REAL HTTP server:
one persistent SigV4-signing connection per simulated client, open-loop
pacing (a client sleeps until each request's scheduled offset; when the
server falls behind, requests queue on the connection and the attained
rate — recorded honestly — drops below the scheduled rate).  After the
replay the engine closes the loop through the server's own accounting:

* ``GET /minio/admin/v3/slo?window=<scenario>`` answers the per-class
  availability/p99 the scenario asserts (the server's ring-buffer
  histograms, not a client stopwatch);
* on ANY violation, ``GET /minio/admin/v3/trace/summary`` (the retained
  tail-capture store) attributes the violation to the dominant span
  stage — WHICH stage ate the p99, not just that it was eaten.

Chaos hooks (ChaosDisk faults, pool drain, worker kill) are armed by
name: the caller supplies ``{name: (start_fn, stop_fn)}`` — the hooks
need server internals the engine deliberately doesn't know about.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import random
import threading
import time
import urllib.parse

from minio_tpu.server import sigv4
#: nearest-rank quantile shared with the trace summary — one
#: definition, so client-side and trace-derived percentiles can't
#: silently diverge
from minio_tpu.utils.tracing import quantile as _pctl

#: ops the schedule can carry; "mpu" is one *logical* request that the
#: engine executes as create + parts + complete (all MULTIPART-class on
#: the server side, one latency sample on the client side)
OPS = ("get", "head", "put", "list", "delete", "mpu")


def _rng(sc, tag: str) -> random.Random:
    # string seeds hash deterministically across runs/platforms in
    # random.Random's version-2 seeding
    return random.Random(f"{sc.seed}:{tag}")


#: catalog memo — GET verification reads it per sample, inside the
#: latency-timed section, so rebuilding the seeded RNG draws per
#: request would both waste the shared box's CPU and inflate the
#: client-side latencies the per-bucket SLO clauses assert against
_catalog_cache: dict[tuple, dict] = {}


def catalog(sc) -> dict[str, dict[str, int]]:
    """bucket -> key -> size; the setup PUTs and GET verification both
    derive from this (bodies via :func:`body_bytes`).  Memoized on the
    fields that determine it."""
    key = (sc.seed, sc.buckets, sc.nobjects, sc.obj_bytes)
    got = _catalog_cache.get(key)
    if got is not None:
        return got
    out: dict[str, dict[str, int]] = {}
    for bucket in sc.buckets:
        rng = _rng(sc, f"catalog:{bucket}")
        lo, hi = sc.obj_bytes
        out[bucket] = {f"o{i:04d}": rng.randint(lo, hi)
                       for i in range(sc.nobjects)}
    if len(_catalog_cache) > 64:
        _catalog_cache.clear()
    _catalog_cache[key] = out
    return out


def body_bytes(sc, tag: str, size: int) -> bytes:
    return _rng(sc, f"body:{tag}").randbytes(size)


def _zipf_weights(n: int, s: float) -> list[float]:
    w = [1.0 / (i ** s) for i in range(1, n + 1)]
    tot = sum(w)
    return [x / tot for x in w]


def build_schedule(sc) -> list[dict]:
    """Deterministic arrival schedule: Poisson arrivals at ``sc.rate``
    over ``sc.duration_s``, ops drawn by weight, keys by shape (zipf
    over the catalog for reads, fresh ``w``-keys for writes, earlier
    ``w``-keys for deletes).  Pure function of the scenario."""
    rng = _rng(sc, "schedule")
    names = sorted(catalog(sc)[sc.buckets[0]])
    zw = _zipf_weights(len(names), sc.zipf_s)
    ops = [op for op, _ in sc.ops]
    weights = [w for _, w in sc.ops]
    # per-bucket workload override (ISSUE 18): one choices() draw per
    # request either way, so scenarios without bucket_ops keep their
    # exact RNG stream (and their pinned digests)
    bops = {b: ([o for o, _ in mix], [w for _, w in mix])
            for b, mix in (getattr(sc, "bucket_ops", None) or {}).items()}
    bops_post = {b: ([o for o, _ in mix], [w for _, w in mix])
                 for b, mix in (getattr(sc, "bucket_ops_post_flip",
                                        None) or {}).items()}
    bclients = getattr(sc, "bucket_clients", None) or {}
    quiet = list(sc.buckets[1:]) or list(sc.buckets)
    profile = getattr(sc, "rate_profile", ()) or ()
    flip_frac = getattr(sc, "mix_flip_at_frac", None)
    flip_at = None if flip_frac is None else flip_frac * sc.duration_s

    def rate_at(now: float) -> float:
        # piecewise regime-shift multiplier (ISSUE 18): still a pure
        # function of the scenario, so the digest pins the shift
        for lo, hi, mult in profile:
            if lo * sc.duration_s <= now < hi * sc.duration_s:
                return sc.rate * mult
        return sc.rate

    sched: list[dict] = []
    written: dict[str, list[str]] = {b: [] for b in sc.buckets}
    t = 0.0
    i = 0
    while True:
        t += rng.expovariate(rate_at(t))
        if t >= sc.duration_s:
            break
        if flip_at is not None and sc.hot_bucket_frac is not None:
            # tenant-mix flip: the hot role moves to buckets[1]; the
            # displaced bucket joins the quiet set.  Gated on the flip
            # field so pre-existing scenarios keep their exact RNG
            # stream (and therefore their pinned schedule digests).
            hot_i = 0 if t < flip_at else 1 % len(sc.buckets)
            others = [b for j, b in enumerate(sc.buckets)
                      if j != hot_i] or list(sc.buckets)
            bucket = sc.buckets[hot_i] \
                if rng.random() < sc.hot_bucket_frac \
                else others[rng.randrange(len(others))]
        elif sc.hot_bucket_frac is not None:
            bucket = sc.buckets[0] if rng.random() < sc.hot_bucket_frac \
                else quiet[rng.randrange(len(quiet))]
        else:
            bucket = sc.buckets[rng.randrange(len(sc.buckets))]
        cur = bops
        if bops_post and flip_at is not None and t >= flip_at \
                and bucket in bops_post:
            cur = bops_post  # the flood itself moved tenants
        b_ops, b_weights = cur.get(bucket, (ops, weights))
        op = rng.choices(b_ops, weights=b_weights)[0]
        ent = {"i": i, "t": round(t, 6), "client": i % sc.clients,
               "op": op, "bucket": bucket}
        span = bclients.get(bucket)
        if span is not None:
            # dedicated pool: the bucket's own clients, round-robin
            ent["client"] = span[0] + i % span[1]
        if op in ("get", "head"):
            ent["key"] = rng.choices(names, weights=zw)[0]
        elif op == "put":
            key = f"w{i:06d}"
            ent["key"] = key
            ent["size"] = rng.randint(*sc.put_bytes)
            written[bucket].append(key)
        elif op == "delete":
            prior = written[bucket]
            if prior:
                ent["key"] = prior[rng.randrange(len(prior))]
            else:
                # nothing written yet: a delete of a catalog key would
                # break later reads; deleting a never-written w-key is
                # the S3-idempotent 204
                ent["key"] = f"w-missing-{i:06d}"
        elif op == "list":
            # a tens-bucket of the o%04d catalog keys: "o003" matches
            # o0030..o0039 — every scheduled prefix walks real entries
            ent["prefix"] = \
                f"o{rng.randrange((sc.nobjects + 9) // 10):03d}"
            ent["max_keys"] = sc.list_max_keys
        elif op == "mpu":
            ent["key"] = f"mpu{i:06d}"
            ent["parts"] = sc.mpu_parts
            ent["part_size"] = sc.mpu_part_bytes
            ent["last_size"] = sc.mpu_last_bytes
        i += 1
        sched.append(ent)
    return sched


def schedule_digest(schedule: list[dict]) -> str:
    """The reproducibility pin recorded per scenario in SIM_r01.json."""
    return hashlib.sha256(json.dumps(
        schedule, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()




class _ClientConn:
    """One simulated client: persistent connection + SigV4 signing.
    Reconnects on transport failure (counted by the caller)."""

    def __init__(self, host: str, port: int, ak: str, sk: str,
                 timeout: float = 60.0):
        self.host, self.port = host, port
        self.ak, self.sk = ak, sk
        self.timeout = timeout
        self._conn: http.client.HTTPConnection | None = None

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def request(self, method: str, path: str, query=(), data=b"",
                headers=None) -> tuple[int, bytes, dict]:
        query = list(query)
        headers = dict(headers or {})
        headers["host"] = f"{self.host}:{self.port}"
        signed = sigv4.sign_request(method, path, query, headers,
                                    data or b"", self.ak, self.sk)
        qs = "&".join(
            f"{urllib.parse.quote(k, safe='')}="
            f"{urllib.parse.quote(v, safe='')}" for k, v in query)
        url = urllib.parse.quote(path) + ("?" + qs if qs else "")
        try:
            conn = self._connection()
            conn.request(method, url, body=data or None, headers=signed)
            r = conn.getresponse()
            body = r.read()
            return r.status, body, dict(r.getheaders())
        except Exception:
            # drop the broken connection; the next request reconnects
            self.close()
            raise


class ScenarioEngine:
    """Replays scenarios against a live server and renders verdicts.

    ``chaos_hooks``: ``{name: (start_fn, stop_fn)}`` armed when a
    scenario names one.  ``slo_slot_s`` must match the server's
    ``MINIO_TPU_SLO_SLOT_S`` — the engine waits one slot after a replay
    so the scenario's slots are complete before it asks the server."""

    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, chaos_hooks: dict | None = None,
                 slo_slot_s: float = 1.0, log=None):
        self.host, self.port = host, port
        self.ak, self.sk = access_key, secret_key
        self.chaos_hooks = chaos_hooks or {}
        self.slo_slot_s = slo_slot_s
        self._log = log or (lambda *_: None)

    # ------------------------------------------------------------ admin
    def _admin(self, method: str, path: str, query=(), data=b""):
        c = _ClientConn(self.host, self.port, self.ak, self.sk)
        try:
            return c.request(method, path, query, data)
        finally:
            c.close()

    def admin_json(self, method: str, path: str, query=(), data=b""):
        status, body, _ = self._admin(method, path, query, data)
        if status != 200:
            raise RuntimeError(
                f"{method} {path} -> {status}: {body[:200]!r}")
        return json.loads(body)

    # ------------------------------------------------------------ setup
    def setup(self, sc) -> None:
        """Buckets + catalog objects (idempotent: overwrites)."""
        c = _ClientConn(self.host, self.port, self.ak, self.sk)
        try:
            for bucket, keys in catalog(sc).items():
                status, _, _ = c.request("PUT", f"/{bucket}")
                if status not in (200, 409):
                    raise RuntimeError(
                        f"create bucket {bucket}: {status}")
                for key, size in keys.items():
                    body = body_bytes(sc, f"{bucket}/{key}", size)
                    status, _, _ = c.request(
                        "PUT", f"/{bucket}/{key}", data=body)
                    if status != 200:
                        raise RuntimeError(
                            f"seed {bucket}/{key}: {status}")
        finally:
            c.close()

    # ----------------------------------------------------------- replay
    def _execute(self, sc, conn: _ClientConn, ent: dict) -> dict:
        op = ent["op"]
        bucket = ent["bucket"]
        # synthesize request payloads BEFORE the latency clock starts:
        # seeded-RNG body generation is client-side work, not server
        # latency (same reasoning as the catalog memo)
        payload = None
        if op == "put":
            payload = body_bytes(sc, f"put:{ent['i']}", ent["size"])
        elif op == "mpu":
            payload = [body_bytes(
                sc, f"mpu:{ent['i']}:{pn}",
                ent["part_size"] if pn < ent["parts"]
                else ent["last_size"])
                for pn in range(1, ent["parts"] + 1)]
        t0 = time.perf_counter()
        status = 0
        err = ""
        try:
            if op in ("get", "head"):
                status, body, _ = conn.request(
                    "GET" if op == "get" else "HEAD",
                    f"/{bucket}/{ent['key']}")
                if op == "get" and status == 200:
                    want = catalog(sc)[bucket][ent["key"]]
                    if len(body) != want:
                        err = f"short body {len(body)} != {want}"
            elif op == "put":
                status, _, _ = conn.request(
                    "PUT", f"/{bucket}/{ent['key']}", data=payload)
            elif op == "delete":
                status, _, _ = conn.request(
                    "DELETE", f"/{bucket}/{ent['key']}")
            elif op == "list":
                status, _, _ = conn.request(
                    "GET", f"/{bucket}",
                    query=[("list-type", "2"),
                           ("prefix", ent["prefix"]),
                           ("max-keys", str(ent["max_keys"]))])
            elif op == "mpu":
                status = self._execute_mpu(conn, ent, payload)
        except Exception as e:  # transport failure
            status = -1
            err = repr(e)
        dur = time.perf_counter() - t0
        api_cls = {"get": "GET", "head": "GET", "put": "PUT",
                   "delete": "DELETE", "list": "LIST",
                   "mpu": "MULTIPART"}[op]
        return {"op": op, "cls": api_cls, "bucket": bucket,
                "status": status, "dur": dur, "err": err}

    def _execute_mpu(self, conn: _ClientConn, ent: dict,
                     parts: list[bytes]) -> int:
        key = ent["key"]
        path = f"/{ent['bucket']}/{key}"
        status, body, _ = conn.request("POST", path,
                                       query=[("uploads", "")])
        if status != 200:
            return status
        text = body.decode(errors="replace")
        lo = text.find("<UploadId>")
        hi = text.find("</UploadId>")
        if lo < 0 or hi < 0:
            return -1
        upload_id = text[lo + len("<UploadId>"):hi]

        def fail(st: int) -> int:
            # abort the dangling upload so a chaos-failed attempt
            # doesn't leak staged parts into the rest of the run
            try:
                conn.request("DELETE", path,
                             query=[("uploadId", upload_id)])
            except Exception:
                pass
            return st

        etags = []
        for pn, part in enumerate(parts, start=1):
            status, _, hdrs = conn.request(
                "PUT", path, data=part,
                query=[("partNumber", str(pn)),
                       ("uploadId", upload_id)])
            if status != 200:
                return fail(status)
            etags.append((pn, hdrs.get("ETag", hdrs.get("Etag", ""))))
        xml = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{pn}</PartNumber><ETag>{etag}</ETag>"
            f"</Part>" for pn, etag in etags) \
            + "</CompleteMultipartUpload>"
        status, _, _ = conn.request(
            "POST", path, data=xml.encode(),
            query=[("uploadId", upload_id)])
        return fail(status) if status != 200 else status

    def replay(self, sc, schedule: list[dict]
               ) -> tuple[list[dict], float, float]:
        """Run the schedule with ``sc.clients`` threads; returns
        (samples, wall_seconds, replay_t0) — ``replay_t0`` is the
        perf-counter instant the clients were released, the anchor for
        the asserted SLO window.  Chaos (when named) is armed by a
        timer thread against the registered hook."""
        chaos = None
        if sc.chaos:
            chaos = self.chaos_hooks.get(sc.chaos)
            if chaos is None:
                # a silent no-op here would record a chaos "pass" in
                # which the fault never happened — the regression
                # surface would quietly stop testing fault tolerance.
                # Checked BEFORE any client thread starts, so nothing
                # is left parked on the barrier.
                raise ValueError(
                    f"scenario {sc.name!r} names chaos hook "
                    f"{sc.chaos!r} but no such hook is registered "
                    f"(have: {sorted(self.chaos_hooks)})")
        samples: list[list[dict]] = [[] for _ in range(sc.clients)]
        barrier = threading.Barrier(sc.clients + 1)
        per_client = [[e for e in schedule if e["client"] == idx]
                      for idx in range(sc.clients)]
        t_start = [0.0]

        def worker(idx: int) -> None:
            conn = _ClientConn(self.host, self.port, self.ak, self.sk)
            try:
                barrier.wait(30)
                base = t_start[0]
                for ent in per_client[idx]:
                    delay = base + ent["t"] - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    samples[idx].append(self._execute(sc, conn, ent))
            finally:
                conn.close()

        # lint: allow(budget-propagation): simulated CLIENTS — load generators outside the server's budget plane by definition
        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"sim-client-{i}", daemon=True)
                   for i in range(sc.clients)]
        for th in threads:
            th.start()
        stop_evt = threading.Event()
        chaos_thread = None
        if chaos is not None:
            start_fn, stop_fn = chaos

            def chaos_runner():
                if stop_evt.wait(sc.duration_s * sc.chaos_at_frac):
                    return
                self._log(f"  chaos[{sc.chaos}] armed")
                try:
                    start_fn()
                    stop_evt.wait(sc.duration_s * sc.chaos_dur_frac)
                finally:
                    stop_fn()
                    self._log(f"  chaos[{sc.chaos}] cleared")

            # lint: allow(budget-propagation): chaos timer for the scenario window, not request work
            chaos_thread = threading.Thread(
                target=chaos_runner, name="sim-chaos", daemon=True)
        t0 = time.perf_counter()
        t_start[0] = t0
        barrier.wait(30)
        if chaos_thread is not None:
            chaos_thread.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        stop_evt.set()
        if chaos_thread is not None:
            # the stop hook may poll server state to a terminal
            # condition (the drain hook waits out the decommission) —
            # give it real room; it is bounded by construction and the
            # verdict must reflect its outcome, not race past it
            chaos_thread.join(sc.duration_s + 180)
        return [s for per in samples for s in per], wall, t0

    # ---------------------------------------------------------- verdict
    @staticmethod
    def _aggregate(samples: list[dict], key) -> dict:
        groups: dict[str, dict] = {}
        for s in samples:
            k = key(s)
            d = groups.get(k)
            if d is None:
                d = groups[k] = {"count": 0, "errors": 0, "shed": 0,
                                 "durs": []}
            d["count"] += 1
            if s["status"] == 503:
                d["shed"] += 1
            elif s["status"] < 0 or s["status"] >= 500 or s["err"]:
                d["errors"] += 1
            d["durs"].append(s["dur"])
        out = {}
        for k, d in sorted(groups.items()):
            ds = sorted(d["durs"])
            out[k] = {
                "count": d["count"], "errors": d["errors"],
                "shed": d["shed"],
                "p50Ms": round(_pctl(ds, 0.50) * 1e3, 3),
                "p99Ms": round(_pctl(ds, 0.99) * 1e3, 3),
                "maxMs": round(ds[-1] * 1e3, 3),
            }
        return out

    def _attribute(self, since: float = 0.0) -> dict | None:
        """Dominant-stage attribution from the retained trace store:
        non-root span names ranked by total seconds (the root spans ARE
        the requests; the stages under them are where the time went).
        ``since`` (epoch) scopes the aggregate to this scenario's
        traces — the store spans the whole run, and an earlier
        scenario's 5 MiB part writes must not out-weigh the violating
        scenario's own stages."""
        try:
            doc = self.admin_json(
                "GET", "/minio/admin/v3/trace/summary",
                query=[("since", f"{since:.3f}")] if since else [])
        except Exception as e:
            return {"error": f"trace summary unavailable: {e!r}"}
        stages = {name: d for name, d in doc.get("spans", {}).items()
                  if not d.get("isRoot")}
        if not stages:
            return {"error": "no retained spans to attribute"}
        ranked = sorted(stages.items(), key=lambda kv: -kv[1]["totalS"])
        name, top = ranked[0]
        try:
            slow = self.admin_json("GET", "/minio/admin/v3/trace/slow",
                                   query=[("n", "50")])
            # scope to this scenario like the summary: the store spans
            # the whole run and a newest-first backfill would point
            # the investigator at another scenario's traces
            trace_ids = [t.get("traceId")
                         for t in slow.get("traces", [])
                         if t.get("start", 0.0) >= since][:5]
        except Exception:
            trace_ids = []
        return {
            "dominantStage": name,
            "totalS": top["totalS"], "count": top["count"],
            "p99Ms": top["p99Ms"],
            "top": [{"stage": n, "totalS": d["totalS"],
                     "p99Ms": d["p99Ms"]} for n, d in ranked[:3]],
            "slowTraceIds": trace_ids,
            "tracesAggregated": doc.get("traces", 0),
        }

    def run(self, sc) -> dict:
        """setup -> (qos apply) -> replay -> server-side SLO assertion
        -> (forensics on violation) -> scenario doc."""
        self._log(f"scenario {sc.name}: setup")
        self.setup(sc)
        schedule = build_schedule(sc)
        digest = schedule_digest(schedule)
        qos_applied = False
        try:
            if sc.qos is not None:
                self.admin_json("PUT", "/minio/admin/v3/qos",
                                data=json.dumps(sc.qos).encode())
                qos_applied = True
            # let the setup PUTs' slots close so the scenario window
            # below measures replay traffic, not catalog seeding: the
            # trailing window's FLOOR slot is included whole by
            # _Ring.agg_windows, so the gap must span two full slots
            time.sleep(self.slo_slot_s * 2.1)
            self._log(f"scenario {sc.name}: replaying "
                      f"{len(schedule)} requests over "
                      f"{sc.duration_s:g}s")
            replay_wall0 = time.time()
            samples, wall, replay_t0 = self.replay(sc, schedule)
        finally:
            if qos_applied:
                try:
                    self.admin_json("PUT", "/minio/admin/v3/qos",
                                    data=json.dumps(
                                        {"enable": False}).encode())
                except Exception as e:
                    # a failed revert must not mask the replay's own
                    # exception — but it must be LOUD: the shared
                    # server is left throttled for whatever runs next
                    self._log(f"scenario {sc.name}: QOS REVERT "
                              f"FAILED ({e!r}) — plane left enabled")
        # let the scenario's final slot close before asking the server
        time.sleep(self.slo_slot_s * 1.1)
        # the window is a TRAILING window anchored at query time, so it
        # must reach back to replay START — a chaos stop hook that
        # polled server state after the workers finished (the drain
        # hook) would otherwise push the replay's head out of the
        # asserted window
        window = (time.perf_counter() - replay_t0) + self.slo_slot_s
        server = self.admin_json("GET", "/minio/admin/v3/slo",
                                 query=[("window", f"{window:.3f}")])
        by_class = self._aggregate(samples, lambda s: s["cls"])
        by_bucket = self._aggregate(samples, lambda s: s["bucket"])
        total = len(samples)
        sheds = sum(1 for s in samples if s["status"] == 503)
        shed_fraction = sheds / total if total else 0.0

        violations: list[str] = []
        slo = sc.slo or {}
        if not server.get("enabled"):
            violations.append("slo-plane-disabled")
        for cls, targets in sorted((slo.get("classes") or {}).items()):
            srv = (server.get("classes") or {}).get(cls)
            win = (srv or {}).get("window") or {}
            if srv is None or not win.get("requests"):
                violations.append(f"{cls}:no-server-data")
                continue
            tgt_p99 = targets.get("p99_ms")
            if tgt_p99 is not None and win.get("p99Ms") is not None \
                    and win["p99Ms"] > tgt_p99:
                violations.append(
                    f"{cls}:latency p99 {win['p99Ms']}ms > "
                    f"{tgt_p99}ms")
            tgt_av = targets.get("availability")
            if tgt_av is not None and win.get("availability") is not None \
                    and win["availability"] < tgt_av:
                violations.append(
                    f"{cls}:availability {win['availability']} < "
                    f"{tgt_av}")
        max_shed = slo.get("shed_fraction_max")
        if max_shed is not None and shed_fraction > max_shed:
            violations.append(
                f"shed fraction {shed_fraction:.4f} > {max_shed}")
        for bucket, targets in sorted((slo.get("buckets") or {}).items()):
            b = by_bucket.get(bucket)
            if b is None:
                violations.append(f"bucket:{bucket}:no-traffic")
                continue
            tgt_p99 = targets.get("p99_ms")
            if tgt_p99 is not None and b["p99Ms"] > tgt_p99:
                violations.append(
                    f"bucket:{bucket}: p99 {b['p99Ms']}ms > "
                    f"{tgt_p99}ms")
            tgt_p50 = targets.get("p50_ms")
            if tgt_p50 is not None and b["p50Ms"] > tgt_p50:
                violations.append(
                    f"bucket:{bucket}: p50 {b['p50Ms']}ms > "
                    f"{tgt_p50}ms")
            shed_max = targets.get("shed_max")
            if shed_max is not None and b["shed"] > shed_max:
                violations.append(
                    f"bucket:{bucket}: {b['shed']} sheds > {shed_max}")
            shed_frac = targets.get("shed_frac_max")
            if shed_frac is not None and b["count"] \
                    and b["shed"] / b["count"] > shed_frac:
                violations.append(
                    f"bucket:{bucket}: shed fraction "
                    f"{b['shed'] / b['count']:.4f} > {shed_frac}")

        doc = {
            "name": sc.name,
            "description": sc.description,
            "seed": sc.seed,
            "durationS": sc.duration_s,
            "clients": sc.clients,
            "scheduledRate": sc.rate,
            "chaos": sc.chaos,
            "scheduleRequests": len(schedule),
            "scheduleSha256": digest,
            "wallS": round(wall, 3),
            "attainedReqPerS": round(total / wall, 3) if wall else 0.0,
            "shedFraction": round(shed_fraction, 6),
            "byClass": by_class,
            "byBucket": by_bucket if len(sc.buckets) > 1 else None,
            "serverSlo": {
                "enabled": server.get("enabled"),
                "windowS": window,
                "classes": {
                    cls: d.get("window")
                    for cls, d in (server.get("classes") or {}).items()},
                "burn": {
                    cls: d.get("burn")
                    for cls, d in (server.get("classes") or {}).items()},
                "tenants": server.get("tenants"),
            },
            "violations": violations,
            "verdict": "pass" if not violations else "fail",
            # 0.5s slack: a trace that began just before the replay
            # clock tick still belongs to this scenario
            "attribution": self._attribute(
                since=replay_wall0 - 0.5) if violations else None,
        }
        self._log(f"scenario {sc.name}: {doc['verdict']}"
                  + (f" ({violations})" if violations else ""))
        return doc

    def run_all(self, scenarios, capacity_probe: dict | None = None
                ) -> dict:
        results = [self.run(sc) for sc in scenarios]
        doc = {
            "schema": 1,
            "scenarios": results,
            "passCount": sum(1 for r in results
                             if r["verdict"] == "pass"),
            "failCount": sum(1 for r in results
                             if r["verdict"] == "fail"),
        }
        if capacity_probe:
            doc["capacityModel"] = self.capacity_model(
                results, capacity_probe)
        return doc

    @staticmethod
    def capacity_model(results: list[dict],
                       probe: dict) -> dict:
        """Fit of attained req/s against the box probes' effective
        cores (PR 8's ``_probe_effective_cores``): a deliberately
        simple linear model ``req/s ~= k * cores`` per scenario shape,
        so future PRs regress against a surface — 'zipf fan-in dropped
        from 41 to 28 req/s/core' — instead of anecdotes."""
        cores = max(float(probe.get("effectiveCores", 1.0)), 1e-6)
        points = [{"scenario": r["name"],
                   "attainedReqPerS": r["attainedReqPerS"],
                   "scheduledRate": r["scheduledRate"],
                   "chaos": r["chaos"],
                   "reqPerSPerCore": round(
                       r["attainedReqPerS"] / cores, 3)}
                  for r in results]
        clean = [p["reqPerSPerCore"] for p in points
                 if not p["chaos"]]
        return {
            "probe": probe,
            "points": points,
            "cleanReqPerSPerCore": {
                "max": max(clean) if clean else None,
                "min": min(clean) if clean else None,
            },
            "model": "req_per_s ≈ k × effective_cores; k per scenario "
                     "shape in points[].reqPerSPerCore (chaos "
                     "scenarios excluded from the clean envelope)",
        }
