"""Object metadata model and the on-drive `xl.meta` document.

Capability-equivalent to the reference's FileInfo/ErasureInfo
(cmd/storage-datatypes.go:117, cmd/erasure-metadata.go) and the xl.meta v2
multi-version file (cmd/xl-storage-format-v2.go): every shard file is
accompanied by a self-describing msgpack document carrying the EC
parameters, the per-part bitrot checksums, the drive distribution, and all
object versions (incl. delete markers and optional inlined small-object
data) — so any surviving read quorum can reconstruct the object without
external state.

Format here is our own msgpack schema (versioned, field-named) rather than
a byte-clone of minio's msgp structs; self-description and quorum
semantics match.
"""

from __future__ import annotations

import secrets
import time
import uuid
from dataclasses import dataclass, field

import msgpack

XL_META_FORMAT = 1
ERASURE_ALGO = "rs-vandermonde"  # reference: "rs-vandermonde" ReedSolomon
NULL_VERSION_ID = "null"


@dataclass
class ChecksumInfo:
    """Bitrot checksum for one part on one drive
    (reference ChecksumInfo, cmd/erasure-metadata.go:37)."""

    part_number: int
    algorithm: str  # "highwayhash256S" (streaming) etc.
    hash: bytes     # empty for streaming bitrot (hashes interleaved in file)


@dataclass
class ErasureInfo:
    """EC geometry for one object version on one drive
    (reference ErasureInfo, cmd/erasure-metadata.go:60)."""

    algorithm: str
    data_blocks: int
    parity_blocks: int
    block_size: int
    index: int                 # 1-based shard index this drive holds
    distribution: list[int]    # hashOrder drive shuffle
    checksums: list[ChecksumInfo] = field(default_factory=list)

    @property
    def shard_size(self) -> int:
        return -(-self.block_size // self.data_blocks)

    def shard_file_size(self, total: int) -> int:
        if total == 0:
            return 0
        if total == -1:
            return -1
        num = total // self.block_size
        last = total % self.block_size
        last_shard = -(-last // self.data_blocks) if last else 0
        return num * self.shard_size + last_shard


@dataclass
class ObjectPartInfo:
    number: int
    size: int            # plaintext part size
    actual_size: int     # pre-compression size
    mod_time: float = 0.0
    etag: str = ""


@dataclass
class FileInfo:
    """One object version as stored on one drive (reference FileInfo)."""

    volume: str = ""
    name: str = ""
    version_id: str = ""
    is_latest: bool = True
    deleted: bool = False          # delete marker
    data_dir: str = ""
    mod_time: float = 0.0
    size: int = 0
    metadata: dict = field(default_factory=dict)
    parts: list[ObjectPartInfo] = field(default_factory=list)
    erasure: ErasureInfo | None = None
    # small objects: shard bytes inlined into xl.meta (cmd/xl-storage.go:59)
    data: bytes | None = None
    fresh: bool = False
    idx: int = 0

    def shard_file_size(self, part_size: int) -> int:
        assert self.erasure is not None
        return self.erasure.shard_file_size(part_size)

    def to_obj(self) -> dict:
        d = {
            "v": self.version_id,
            "del": self.deleted,
            "dd": self.data_dir,
            "mt": self.mod_time,
            "sz": self.size,
            "meta": self.metadata,
            "parts": [
                {"n": p.number, "s": p.size, "as": p.actual_size,
                 "mt": p.mod_time, "e": p.etag}
                for p in self.parts
            ],
        }
        if self.erasure is not None:
            e = self.erasure
            d["ec"] = {
                "algo": e.algorithm, "k": e.data_blocks, "m": e.parity_blocks,
                "bs": e.block_size, "ix": e.index, "dist": e.distribution,
                "cs": [
                    {"p": c.part_number, "a": c.algorithm, "h": c.hash}
                    for c in e.checksums
                ],
            }
        if self.data is not None:
            d["data"] = self.data
        return d

    @classmethod
    def from_obj(cls, volume: str, name: str, d: dict) -> "FileInfo":
        ec = None
        if "ec" in d:
            e = d["ec"]
            ec = ErasureInfo(
                algorithm=e["algo"], data_blocks=e["k"], parity_blocks=e["m"],
                block_size=e["bs"], index=e["ix"], distribution=list(e["dist"]),
                checksums=[
                    ChecksumInfo(c["p"], c["a"], c["h"]) for c in e.get("cs", [])
                ],
            )
        return cls(
            volume=volume, name=name, version_id=d.get("v", ""),
            deleted=d.get("del", False), data_dir=d.get("dd", ""),
            mod_time=d.get("mt", 0.0), size=d.get("sz", 0),
            metadata=dict(d.get("meta", {})),
            parts=[
                ObjectPartInfo(p["n"], p["s"], p["as"], p.get("mt", 0.0),
                               p.get("e", ""))
                for p in d.get("parts", [])
            ],
            erasure=ec,
            data=d.get("data"),
        )


def new_version_id() -> str:
    return str(uuid.uuid4())


def new_data_dir() -> str:
    return str(uuid.UUID(bytes=secrets.token_bytes(16)))


class XLMeta:
    """Multi-version xl.meta document for one object on one drive."""

    def __init__(self, versions: list[dict] | None = None):
        # newest first, like the reference's sorted version headers
        self.versions: list[dict] = versions or []

    # -- serialization ------------------------------------------------------
    def dumps(self) -> bytes:
        return msgpack.packb(
            {"fmt": XL_META_FORMAT, "vers": self.versions}, use_bin_type=True
        )

    @classmethod
    def loads(cls, raw: bytes) -> "XLMeta":
        doc = msgpack.unpackb(raw, raw=False, strict_map_key=False)
        if doc.get("fmt") != XL_META_FORMAT:
            raise ValueError(f"unsupported xl.meta format {doc.get('fmt')}")
        return cls(doc.get("vers", []))

    # -- version operations -------------------------------------------------
    def add_version(self, fi: FileInfo) -> dict | None:
        """Insert a version, replacing any same-id entry.  Returns the
        replaced entry (if any) so the caller can reclaim its data dir."""
        obj = fi.to_obj()
        vid = obj.get("v", "")
        replaced = None
        kept = []
        for v in self.versions:
            if v.get("v", "") == vid:
                replaced = v
            else:
                kept.append(v)
        self.versions = kept
        self.versions.insert(0, obj)
        self.versions.sort(key=lambda v: v.get("mt", 0.0), reverse=True)
        return replaced

    def delete_version(self, version_id: str) -> dict | None:
        # the API-level sentinel "null" addresses the internal empty-id
        # version (the "null version" written while versioning is off or
        # suspended — reference nullVersionID, cmd/xl-storage-format-v2.go)
        if version_id == NULL_VERSION_ID:
            version_id = ""
        for i, v in enumerate(self.versions):
            if v.get("v", "") == version_id:
                return self.versions.pop(i)
        return None

    def find_version(self, version_id: str) -> dict | None:
        if version_id == NULL_VERSION_ID:
            for v in self.versions:
                if v.get("v", "") == "":
                    return v
            return None
        if not version_id:
            return self.versions[0] if self.versions else None
        for v in self.versions:
            if v.get("v", "") == version_id:
                return v
        return None

    @property
    def latest(self) -> dict | None:
        return self.versions[0] if self.versions else None


def file_info_from_raw(raw: bytes, volume: str, name: str,
                       version_id: str = "", read_data: bool = False) -> FileInfo:
    xl = XLMeta.loads(raw)
    v = xl.find_version(version_id)
    if v is None:
        from . import errors
        raise errors.FileVersionNotFound(f"{volume}/{name}@{version_id}")
    fi = FileInfo.from_obj(volume, name, v)
    fi.is_latest = xl.versions and xl.versions[0].get("v", "") == fi.version_id
    if not read_data:
        fi.data = None
    return fi


def find_file_info_in_quorum(parts_metadata: list[FileInfo | None],
                             quorum: int) -> FileInfo:
    """Pick the FileInfo agreed by >= quorum drives.

    Mirrors findFileInfoInQuorum (cmd/erasure-metadata.go:285): drives vote
    with a hash over (mod_time, data_dir, EC geometry, distribution); the
    modal variant wins if it meets quorum.
    """
    from . import errors

    counts: dict = {}
    for fi in parts_metadata:
        if fi is None:
            continue
        e = fi.erasure
        sig = (
            round(fi.mod_time, 6), fi.data_dir, fi.deleted, fi.version_id,
            None if e is None else (
                e.data_blocks, e.parity_blocks, e.block_size,
                tuple(e.distribution),
            ),
        )
        counts[sig] = counts.get(sig, 0) + 1
    if not counts:
        raise errors.ErasureReadQuorum("no metadata read")
    best = max(counts, key=lambda s: counts[s])
    if counts[best] < quorum:
        raise errors.ErasureReadQuorum(
            f"metadata quorum not met: {counts[best]} < {quorum}"
        )
    for fi in parts_metadata:
        if fi is None:
            continue
        e = fi.erasure
        sig = (
            round(fi.mod_time, 6), fi.data_dir, fi.deleted, fi.version_id,
            None if e is None else (
                e.data_blocks, e.parity_blocks, e.block_size,
                tuple(e.distribution),
            ),
        )
        if sig == best:
            return fi
    raise errors.ErasureReadQuorum("unreachable")


def object_quorum_from_meta(parts_metadata: list[FileInfo | None],
                            default_parity: int) -> tuple[int, int]:
    """(read_quorum, write_quorum) from stored EC geometry
    (cmd/erasure-metadata.go:391)."""
    parity = default_parity
    for fi in parts_metadata:
        if fi is not None and fi.erasure is not None:
            parity = fi.erasure.parity_blocks
            data = fi.erasure.data_blocks
            break
    else:
        data = None
    if data is None:
        n = len(parts_metadata)
        data = n - parity
    write_q = data + 1 if data == parity else data
    return data, write_q
