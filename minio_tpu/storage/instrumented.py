"""Instrumented per-drive decorator: per-op counters + EWMA latencies.

Equivalent of the reference's xlStorageDiskIDCheck
(cmd/xl-storage-disk-id-check.go:68): wraps any StorageAPI and records,
per storage operation, the call count, error count, cumulative wall time
and an exponentially-weighted moving average latency.  The numbers feed
the admin StorageInfo plane and the Prometheus drive metrics.
"""

from __future__ import annotations

import threading
import time

# every data-plane method of StorageAPI gets a timer (control accessors
# like disk_id/is_online are left untimed on purpose — they are hot and
# trivially cheap)
TIMED_OPS = (
    "make_volume", "list_volumes", "stat_volume", "delete_volume",
    "read_all", "write_all", "delete", "rename_file", "create_file",
    "open_file_writer", "append_file", "read_file_stream", "read_file",
    "read_version", "read_xl", "write_metadata", "update_metadata",
    "delete_version", "delete_versions", "free_version_data",
    "rename_data",
    "list_dir", "walk_dir",
    "verify_file", "check_parts", "disk_info",
)

EWMA_ALPHA = 0.2  # same smoothing idea as the reference's EWMA latency


class OpStats:
    __slots__ = ("count", "errors", "total_s", "ewma_s", "mu")

    def __init__(self):
        self.count = 0
        self.errors = 0
        self.total_s = 0.0
        self.ewma_s = 0.0
        self.mu = threading.Lock()

    def record(self, dt: float, failed: bool) -> None:
        with self.mu:
            self.count += 1
            if failed:
                self.errors += 1
            self.total_s += dt
            self.ewma_s = (dt if self.count == 1
                           else EWMA_ALPHA * dt
                           + (1 - EWMA_ALPHA) * self.ewma_s)

    def to_dict(self) -> dict:
        with self.mu:
            return {
                "count": self.count, "errors": self.errors,
                "totalSeconds": round(self.total_s, 6),
                "ewmaMillis": round(self.ewma_s * 1e3, 3),
            }


class InstrumentedStorage:
    """Transparent timing wrapper around a StorageAPI instance."""

    def __init__(self, inner):
        self._inner = inner
        self._ops: dict[str, OpStats] = {op: OpStats() for op in TIMED_OPS}
        for op in TIMED_OPS:
            target = getattr(inner, op, None)
            if target is not None:
                setattr(self, op, self._wrap(op, target))

    def _wrap(self, op: str, fn):
        stats = self._ops[op]

        def timed(*a, **kw):
            t0 = time.monotonic()
            try:
                out = fn(*a, **kw)
            except Exception:
                stats.record(time.monotonic() - t0, failed=True)
                raise
            stats.record(time.monotonic() - t0, failed=False)
            return out

        timed.__name__ = op
        return timed

    # untimed passthroughs (and anything a backend adds beyond the ABC)
    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- metrics surface -----------------------------------------------------
    def op_stats(self) -> dict[str, dict]:
        """{op: {count, errors, totalSeconds, ewmaMillis}} for ops used."""
        return {op: s.to_dict() for op, s in self._ops.items() if s.count}

    def unwrap(self):
        return self._inner


def instrument(disks):
    """Wrap a list of drives (None entries pass through)."""
    return [InstrumentedStorage(d) if d is not None
            and not isinstance(d, InstrumentedStorage) else d for d in disks]
