"""Instrumented per-drive decorator: counters, EWMA latencies, and a
drive-health circuit breaker.

Equivalent of the reference's xlStorageDiskIDCheck
(cmd/xl-storage-disk-id-check.go:68): wraps any StorageAPI and records,
per storage operation, the call count, error count, cumulative wall time
and an exponentially-weighted moving average latency.  The numbers feed
the admin StorageInfo plane and the Prometheus drive metrics.

On top of the timers sits the health tracker (the reference's
diskHealthTracker + storage REST client offline marking,
cmd/xl-storage-disk-id-check.go:170, internal/rest/client.go:219):
consecutive drive-level faults trip a circuit breaker that marks the
drive OFFLINE, every further call fails fast with DiskNotFound (no
quorum-path stall behind a hung drive), and a background reconnect
probe flips the drive back online — firing the `on_online` hook so the
owner can enqueue an MRF re-sync of writes the drive missed.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import threading
import time

from minio_tpu.storage import errors
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing

# every data-plane method of StorageAPI gets a timer (control accessors
# like disk_id/is_online are left untimed on purpose — they are hot and
# trivially cheap)
TIMED_OPS = (
    "make_volume", "list_volumes", "stat_volume", "delete_volume",
    "read_all", "write_all", "delete", "rename_file", "create_file",
    "open_file_writer", "append_file", "read_file_stream", "read_file",
    "read_version", "read_xl", "write_metadata", "update_metadata",
    "delete_version", "delete_versions", "free_version_data",
    "rename_data",
    "list_dir", "walk_dir",
    "verify_file", "check_parts", "disk_info",
)

EWMA_ALPHA = 0.2  # same smoothing idea as the reference's EWMA latency
# idle decay half-life for the EWMA (seconds): a drive that stops
# getting samples — e.g. because its slow reads got it hedged out —
# decays toward healthy so it un-hedges WITHOUT needing a probe read
# to refresh the average (ROADMAP deadline/overload follow-up).  A
# hedged-out drive sees no reads, so without decay its last bad EWMA
# would pin it slow forever.  0 disables decay.
EWMA_DECAY_HALFLIFE_S = float(
    os.environ.get("MINIO_TPU_EWMA_DECAY_HALFLIFE_S", "30"))

# consecutive drive-level faults before the breaker opens (reference:
# diskMaxConcurrent/diskActiveMonitoring heuristics collapse to a small
# consecutive-failure threshold here)
BREAKER_THRESHOLD = int(os.environ.get("MINIO_TPU_BREAKER_THRESHOLD", "3"))
# reconnect probe cadence: starts fast, backs off exponentially
PROBE_INTERVAL = float(os.environ.get("MINIO_TPU_PROBE_INTERVAL", "0.5"))
PROBE_MAX_INTERVAL = float(
    os.environ.get("MINIO_TPU_PROBE_MAX_INTERVAL", "5.0"))

# drive-level faults: the transport/medium failed, as opposed to benign
# negative results (FileNotFound & friends prove the drive responded and
# therefore RESET the consecutive-fault counter)
_FAULT_TYPES = (errors.DiskNotFound, errors.FaultyDisk,
                errors.UnformattedDisk)

# read-path ops the per-op deadline worker may abandon mid-call: all
# idempotent and side-effect free, so the orphaned call finishing late
# changes nothing.  Write/commit ops are NEVER abandoned — timing out a
# rename/append the drive then completes would leave state divergent
# (same line the RPC client draws with slow/non-idempotent calls).
DEADLINE_GATED_OPS = frozenset((
    "read_all", "read_version", "read_xl", "read_file_stream",
    "read_file", "list_dir", "list_volumes", "stat_volume",
    "disk_info", "check_parts",
))

_dl_pool_lock = threading.Lock()
_dl_pool: cf.ThreadPoolExecutor | None = None

# a deadline timeout only counts as a drive FAULT (feeding the breaker)
# when the drive had at least this much time to answer — a read
# abandoned because the caller arrived with a sliver of budget proves
# nothing about the drive (a client could otherwise trip every breaker
# with x-amz-request-timeout: 1ms)
DEADLINE_FAULT_MIN = float(
    os.environ.get("MINIO_TPU_DEADLINE_FAULT_MIN", "1.0"))
# the worker-pool detour (submit + context copy + two thread handoffs
# per op) only pays for itself when the remaining budget is TIGHT
# enough that abandoning a hung call matters; relaxed budgets (the
# default 1m) run inline — the RPC per-attempt timeouts and the breaker
# already bound hangs at that horizon, and the hot path stays hop-free
DEADLINE_GATE_MAX = float(
    os.environ.get("MINIO_TPU_DEADLINE_GATE_MAX", "10.0"))


def _deadline_pool() -> cf.ThreadPoolExecutor:
    """Process-wide worker pool running deadline-gated drive reads (the
    reference's per-drive health/deadline goroutines collapse to one
    shared pool here).  Intentionally long-lived, like shard-io.  Sized
    generously: abandoned reads pin a worker until the drive answers,
    and the breaker (which trips hung drives into fast-fails) is what
    keeps that pinning bounded."""
    global _dl_pool
    with _dl_pool_lock:
        if _dl_pool is None:
            _dl_pool = cf.ThreadPoolExecutor(
                max_workers=int(os.environ.get(
                    "MINIO_TPU_DEADLINE_WORKERS", "128")),
                thread_name_prefix="drive-deadline")
        return _dl_pool


def _close_abandoned(fut: cf.Future) -> None:
    """When an abandoned read eventually returns a stream handle, close
    it — nobody else will (keeps remote HTTP conns from lingering)."""
    try:
        out = fut.result()
    except Exception:
        return
    closer = getattr(out, "close", None)
    if closer is not None:
        try:
            closer()
        except Exception:
            pass


def is_drive_fault(e: BaseException) -> bool:
    if isinstance(e, _FAULT_TYPES):
        return True
    if isinstance(e, errors.StorageError):
        return False
    # raw OSError/TimeoutError escaping a backend is a medium fault
    return isinstance(e, (OSError, TimeoutError))


class OpStats:
    __slots__ = ("count", "errors", "total_s", "ewma_s", "last_t", "mu")

    def __init__(self):
        self.count = 0
        self.errors = 0
        self.total_s = 0.0
        self.ewma_s = 0.0
        self.last_t = 0.0  # monotonic time of the last sample
        self.mu = threading.Lock()

    def record(self, dt: float, failed: bool) -> None:
        with self.mu:
            self.count += 1
            if failed:
                self.errors += 1
            self.total_s += dt
            # blend against the new sample CLAMPED into
            # [decayed, raw] history: slow evidence re-validates the
            # old (undecayed) slow average up to its own magnitude —
            # a chronically slow drive on a cold bucket keeps hedging
            # even when each fresh sample sits just under the stale
            # raw average — while a genuinely fast sample tracks the
            # decayed history, so recovery after an idle gap does not
            # resurrect stale slowness.  With no idle gap
            # (decayed == raw) this is exactly the classic EWMA.
            if self.count == 1:
                self.ewma_s = dt
            else:
                base = max(self._decayed_locked(), min(dt, self.ewma_s))
                self.ewma_s = EWMA_ALPHA * dt + (1 - EWMA_ALPHA) * base
            self.last_t = time.monotonic()

    def _decayed_locked(self, now: float | None = None) -> float:
        """EWMA with idle decay applied (caller holds self.mu): halves
        every EWMA_DECAY_HALFLIFE_S without a new sample, so a drive
        that recovered (or stopped being read because hedging steered
        around it) drifts back toward healthy instead of staying
        pinned at its last bad average."""
        if self.count == 0:
            return 0.0
        if EWMA_DECAY_HALFLIFE_S <= 0:
            return self.ewma_s
        idle = (time.monotonic() if now is None else now) - self.last_t
        if idle <= 0:
            return self.ewma_s
        return self.ewma_s * 0.5 ** (idle / EWMA_DECAY_HALFLIFE_S)

    def to_dict(self) -> dict:
        with self.mu:
            return {
                "count": self.count, "errors": self.errors,
                "totalSeconds": round(self.total_s, 6),
                "ewmaMillis": round(self._decayed_locked() * 1e3, 3),
            }


class InstrumentedStorage:
    """Timing + health wrapper around a StorageAPI instance."""

    def __init__(self, inner, breaker_threshold: int | None = None):
        self._inner = inner
        self._ops: dict[str, OpStats] = {op: OpStats() for op in TIMED_OPS}
        self._threshold = (BREAKER_THRESHOLD if breaker_threshold is None
                           else breaker_threshold)
        self._health_mu = threading.Lock()
        self._consec_faults = 0
        self._breaker_open = False
        self._offline_since = 0.0
        self._probe_thread: threading.Thread | None = None
        self._closed = False
        self.trips = 0        # breaker open events
        self.reconnects = 0   # probe-driven recoveries
        self.fast_fails = 0   # calls rejected while the breaker was open
        self.deadline_timeouts = 0  # gated reads abandoned mid-call
        self.deadline_expired = 0   # gated reads refused: budget spent
        self.on_offline = None  # callable(self), fired when the breaker trips
        self.on_online = None   # callable(self), fired when the probe recovers
        for op in TIMED_OPS:
            target = getattr(inner, op, None)
            if target is not None:
                setattr(self, op, self._wrap(op, target))

    def _wrap(self, op: str, fn):
        stats = self._ops[op]
        gated = op in DEADLINE_GATED_OPS

        def timed(*a, **kw):
            if self._breaker_open:
                # fail fast: a tripped drive must cost microseconds, not a
                # full RPC timeout, or one hung drive stalls every quorum
                # write (reference: errDiskNotFound short-circuit)
                with self._health_mu:
                    self.fast_fails += 1
                raise errors.DiskNotFound(
                    f"{self._endpoint_label()}: drive offline "
                    f"(circuit breaker open)")
            budget = deadline_mod.current()
            if gated and budget is not None and budget.t_end is not None \
                    and budget.remaining() <= DEADLINE_GATE_MAX:
                return self._deadline_call(op, fn, stats, budget, a, kw)
            # per-drive op span when a request trace is ambient: the
            # where-did-this-request's-time-go attribution ISSUE 12
            # exists for (one contextvar read when untraced)
            ref = tracing.current_ref()
            t0 = time.monotonic()
            try:
                out = fn(*a, **kw)
            except Exception as e:
                dt = time.monotonic() - t0
                stats.record(dt, failed=True)
                if ref is not None:
                    tracing.record_span(
                        ref, f"drive.{op}", dt,
                        drive=self._endpoint_label(),
                        error=type(e).__name__)
                self._note(fault=is_drive_fault(e))
                raise
            dt = time.monotonic() - t0
            stats.record(dt, failed=False)
            if ref is not None:
                tracing.record_span(ref, f"drive.{op}", dt,
                                    drive=self._endpoint_label())
            self._note(fault=False)
            return out

        timed.__name__ = op
        return timed

    def _deadline_call(self, op: str, fn, stats, budget, a, kw):
        """Per-op deadline worker (reference diskHealthCheck contexts,
        cmd/xl-storage-disk-id-check.go): the read runs on the shared
        deadline pool bounded by the request's remaining budget.  A call
        the drive holds past the budget is ABANDONED — the caller gets
        DeadlineExceeded now and the hang feeds the breaker, instead of
        one slow drive holding a quorum fan-out hostage for the full RPC
        timeout."""
        rem = budget.remaining()
        if rem <= 0:
            with self._health_mu:
                self.deadline_expired += 1
            raise errors.DeadlineExceeded(
                f"{self._endpoint_label()}: {op} refused, request "
                f"deadline budget exhausted")
        ref = tracing.current_ref()
        fut = deadline_mod.ctx_submit(_deadline_pool(), fn, *a, **kw)
        t0 = time.monotonic()
        try:
            out = fut.result(timeout=rem)
        except cf.TimeoutError:
            if fut.cancel():
                # never started: pool backlog ate the budget — not this
                # drive's fault; no op sample either (the drive never
                # saw the call, a failed/slow sample would poison the
                # EWMA that steers hedging)
                with self._health_mu:
                    self.deadline_expired += 1
            else:
                stats.record(time.monotonic() - t0, failed=True)
                if ref is not None:
                    # ABANDONED mark only when the drive actually held
                    # the read — a cancel()ed (never-started) call is
                    # the POOL's backlog, and blaming the drive in the
                    # trace would be the exact misattribution this
                    # plane exists to prevent
                    tracing.record_span(ref, f"drive.{op}",
                                        time.monotonic() - t0,
                                        drive=self._endpoint_label(),
                                        abandoned=True)
                fut.add_done_callback(_close_abandoned)
                with self._health_mu:
                    self.deadline_timeouts += 1
                if rem >= DEADLINE_FAULT_MIN:
                    # the drive had a fair window and still held the
                    # read: that is a hang, feed the breaker.  A
                    # sliver-budget abandonment is the CALLER's poverty,
                    # not a drive fault
                    self._note(fault=True)
            raise errors.DeadlineExceeded(
                f"{self._endpoint_label()}: {op} abandoned after "
                f"{rem * 1e3:.0f} ms budget")
        except Exception as e:
            dt = time.monotonic() - t0
            stats.record(dt, failed=True)
            if ref is not None:
                tracing.record_span(ref, f"drive.{op}", dt,
                                    drive=self._endpoint_label(),
                                    error=type(e).__name__)
            self._note(fault=is_drive_fault(e))
            raise
        dt = time.monotonic() - t0
        stats.record(dt, failed=False)
        if ref is not None:
            tracing.record_span(ref, f"drive.{op}", dt,
                                drive=self._endpoint_label())
        self._note(fault=False)
        return out

    def _endpoint_label(self) -> str:
        try:
            return self._inner.endpoint() or repr(self._inner)
        except Exception:
            return repr(self._inner)

    # -- breaker ------------------------------------------------------------
    def _note(self, fault: bool) -> None:
        tripped = False
        with self._health_mu:
            if fault:
                self._consec_faults += 1
                if (not self._breaker_open
                        and self._consec_faults >= self._threshold):
                    self._breaker_open = True
                    self._offline_since = time.time()
                    self.trips += 1
                    tripped = True
            else:
                self._consec_faults = 0
        if tripped:
            self._start_probe()
            cb = self.on_offline
            if cb is not None:
                try:
                    cb(self)
                except Exception:
                    pass

    def _start_probe(self) -> None:
        with self._health_mu:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                return
            t = deadline_mod.service_thread(
                self._probe_loop, start=False,
                name=f"drive-probe-{id(self):x}")
            self._probe_thread = t
        t.start()

    def _probe_loop(self) -> None:
        interval = PROBE_INTERVAL
        while not self._closed:
            time.sleep(interval)
            if self._closed or self._probe_once():
                return
            interval = min(interval * 2, PROBE_MAX_INTERVAL)

    def _probe_once(self) -> bool:
        """One reconnect attempt against the INNER drive (bypassing the
        breaker).  disk_info is the canonical cheap data-plane op; for
        remote drives the RPC client's own short-deadline ping runs
        first so a down peer costs ~nothing."""
        try:
            if not self._inner.is_online():
                return False
            self._inner.disk_info()
        except Exception:
            return False
        with self._health_mu:
            if not self._breaker_open:
                return True  # already recovered elsewhere
            self._breaker_open = False
            self._consec_faults = 0
            self.reconnects += 1
        cb = self.on_online
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass
        return True

    # -- health surface -----------------------------------------------------
    def is_online(self) -> bool:
        if self._breaker_open:
            return False
        try:
            return self._inner.is_online()
        except Exception:
            return False

    def breaker_open(self) -> bool:
        return self._breaker_open

    def health_stats(self) -> dict:
        with self._health_mu:
            return {
                "breakerOpen": self._breaker_open,
                "consecFaults": self._consec_faults,
                "trips": self.trips,
                "reconnects": self.reconnects,
                "fastFails": self.fast_fails,
                "deadlineTimeouts": self.deadline_timeouts,
                "deadlineExpired": self.deadline_expired,
                "offlineSince": (round(self._offline_since, 3)
                                 if self._breaker_open else 0),
            }

    def op_ewma(self, op: str) -> float:
        """EWMA latency (seconds) of one op, with idle decay; 0.0
        before any sample.  The read path uses this to hedge around
        chronically slow drives — decay is what lets a hedged-out
        drive (which by construction gets no new read samples)
        eventually un-hedge without a probe read."""
        s = self._ops.get(op)
        if s is None:
            return 0.0
        with s.mu:
            return s._decayed_locked()

    def close(self) -> None:
        self._closed = True
        self._inner.close()

    # untimed passthroughs (and anything a backend adds beyond the ABC)
    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- metrics surface -----------------------------------------------------
    def op_stats(self) -> dict[str, dict]:
        """{op: {count, errors, totalSeconds, ewmaMillis}} for ops used."""
        return {op: s.to_dict() for op, s in self._ops.items() if s.count}

    def unwrap(self):
        return self._inner


def instrument(disks):
    """Wrap a list of drives (None entries pass through)."""
    return [InstrumentedStorage(d) if d is not None
            and not isinstance(d, InstrumentedStorage) else d for d in disks]
