"""Programmable fault-injection drive for tests and chaos drills.

Reference: cmd/naughty-disk_test.go:31 — wraps a real StorageAPI and
fails specific call numbers with programmed errors (or every call with a
default error), so drive loss and flaky-IO windows can be simulated
mid-operation deterministically.
"""

from __future__ import annotations

import threading

# ops that count toward the programmed call sequence (identity accessors
# never fail — matching the reference, which passes through DiskID etc.)
FAULTABLE_OPS = (
    "make_volume", "list_volumes", "stat_volume", "delete_volume",
    "read_all", "write_all", "delete", "rename_file", "create_file",
    "open_file_writer", "append_file", "read_file_stream", "read_file",
    "read_version", "read_xl", "write_metadata", "update_metadata",
    "delete_version", "delete_versions", "free_version_data",
    "rename_data", "list_dir",
    "walk_dir", "verify_file", "check_parts", "disk_info",
)


class NaughtyDisk:
    """StorageAPI decorator injecting programmed per-call errors.

    errs: {call_number: Exception} — the Nth faultable call (1-based,
    counted across all ops) raises its exception instead of executing.
    default_err: if set, EVERY faultable call not in `errs` raises it
    (an always-broken disk).
    """

    def __init__(self, inner, errs: dict[int, Exception] | None = None,
                 default_err: Exception | None = None):
        self._inner = inner
        self.errs = dict(errs or {})
        self.default_err = default_err
        self.call_count = 0
        self._mu = threading.Lock()
        for op in FAULTABLE_OPS:
            target = getattr(inner, op, None)
            if target is not None:
                setattr(self, op, self._wrap(target))

    def _wrap(self, fn):
        def naughty(*a, **kw):
            with self._mu:
                self.call_count += 1
                n = self.call_count
            if n in self.errs:
                raise self.errs[n]
            if self.default_err is not None:
                raise self.default_err
            return fn(*a, **kw)

        naughty.__name__ = fn.__name__
        return naughty

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def unwrap(self):
        return self._inner
