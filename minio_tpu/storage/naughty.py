"""Programmable fault-injection drives for tests and chaos drills.

Reference: cmd/naughty-disk_test.go:31 — wraps a real StorageAPI and
fails specific call numbers with programmed errors (or every call with a
default error), so drive loss and flaky-IO windows can be simulated
mid-operation deterministically.

Two flavours:

* NaughtyDisk — deterministic per-call-number faults (the reference's
  naughty disk verbatim), for unit tests that need "the 3rd call fails".
* ChaosDisk — time-based programmable faults (latency injection, flaky-IO
  windows, whole-drive loss/restore), drivable in-process or over the
  test-only chaos RPC hook (register_chaos_rpc, enabled by
  MINIO_TPU_CHAOS=1) so distributed kill-drives-and-heal drills can
  inject faults into REMOTE drives behind the storage RPC plane — the
  verify-healing.sh analogue's control surface.
"""

from __future__ import annotations

import threading
import time

from minio_tpu.storage import errors

# ops that count toward the programmed call sequence (identity accessors
# never fail — matching the reference, which passes through DiskID etc.)
FAULTABLE_OPS = (
    "make_volume", "list_volumes", "stat_volume", "delete_volume",
    "read_all", "write_all", "delete", "rename_file", "create_file",
    "open_file_writer", "append_file", "read_file_stream", "read_file",
    "read_version", "read_xl", "write_metadata", "update_metadata",
    "delete_version", "delete_versions", "free_version_data",
    "rename_data", "list_dir",
    "walk_dir", "verify_file", "check_parts", "disk_info",
)


class NaughtyDisk:
    """StorageAPI decorator injecting programmed per-call errors.

    errs: {call_number: Exception} — the Nth faultable call (1-based,
    counted across all ops) raises its exception instead of executing.
    default_err: if set, EVERY faultable call not in `errs` raises it
    (an always-broken disk).
    """

    def __init__(self, inner, errs: dict[int, Exception] | None = None,
                 default_err: Exception | None = None):
        self._inner = inner
        self.errs = dict(errs or {})
        self.default_err = default_err
        self.call_count = 0
        self._mu = threading.Lock()
        for op in FAULTABLE_OPS:
            target = getattr(inner, op, None)
            if target is not None:
                setattr(self, op, self._wrap(target))

    def _wrap(self, fn):
        def naughty(*a, **kw):
            with self._mu:
                self.call_count += 1
                n = self.call_count
            if n in self.errs:
                raise self.errs[n]
            if self.default_err is not None:
                raise self.default_err
            return fn(*a, **kw)

        naughty.__name__ = fn.__name__
        return naughty

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def unwrap(self):
        return self._inner


class ChaosDisk:
    """StorageAPI decorator with time-based programmable faults.

    Unlike NaughtyDisk's per-call-number errors, ChaosDisk models the
    faults a real drive exhibits over wall time:

    * set_latency(s)   — every faultable call sleeps s first (a slow or
                         hung drive; pair with RPC op timeouts)
    * set_flaky(dur)   — calls raise FaultyDisk for the next dur seconds
    * lose()/restore() — whole-drive loss: calls raise DiskNotFound until
                         restored

    All controls are thread-safe and take effect immediately, including
    for in-flight wrappers handed to peers over the storage RPC plane.
    """

    def __init__(self, inner):
        self._inner = inner
        self._mu = threading.Lock()
        self._latency = 0.0
        self._flaky_until = 0.0
        self._lost = False
        self.faults_injected = 0
        for op in FAULTABLE_OPS:
            target = getattr(inner, op, None)
            if target is not None:
                setattr(self, op, self._wrap(target))

    # -- controls ------------------------------------------------------------
    def set_latency(self, seconds: float) -> None:
        with self._mu:
            self._latency = max(0.0, float(seconds))

    def set_flaky(self, duration: float) -> None:
        with self._mu:
            self._flaky_until = time.monotonic() + max(0.0, float(duration))

    def lose(self) -> None:
        with self._mu:
            self._lost = True

    def restore(self) -> None:
        """Clear every programmed fault (drive plugged back in)."""
        with self._mu:
            self._lost = False
            self._latency = 0.0
            self._flaky_until = 0.0

    def status(self) -> dict:
        with self._mu:
            return {
                "lost": self._lost,
                "latency": self._latency,
                "flakyRemaining": round(
                    max(0.0, self._flaky_until - time.monotonic()), 3),
                "faultsInjected": self.faults_injected,
            }

    # -- interposition -------------------------------------------------------
    def _gate(self) -> None:
        with self._mu:
            latency = self._latency
            lost = self._lost
            flaky = time.monotonic() < self._flaky_until
        if latency:
            time.sleep(latency)
        if lost:
            with self._mu:
                self.faults_injected += 1
            raise errors.DiskNotFound(
                f"{getattr(self._inner, 'endpoint', lambda: '?')()} "
                f"(chaos: drive lost)")
        if flaky:
            with self._mu:
                self.faults_injected += 1
            raise errors.FaultyDisk("chaos: flaky-IO window")

    def _wrap(self, fn):
        def chaotic(*a, **kw):
            self._gate()
            return fn(*a, **kw)

        chaotic.__name__ = fn.__name__
        return chaotic

    def is_online(self) -> bool:
        with self._mu:
            if self._lost:
                return False
        return self._inner.is_online()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def unwrap(self):
        return self._inner


def register_chaos_rpc(router, chaos_disks: dict[str, "ChaosDisk"]) -> None:
    """Mount the TEST-ONLY chaos control plane on the RPC router.

    Only wired when the server boots with MINIO_TPU_CHAOS=1
    (distributed/node.py); production processes never expose it.  Calls
    are HMAC-authenticated like every other RPC, so only cluster peers /
    holders of the cluster secret can inject faults.
    """

    def _disk(args) -> "ChaosDisk":
        d = chaos_disks.get(args.get("drive", ""))
        if d is None:
            raise errors.DiskNotFound(args.get("drive", "?"))
        return d

    def inject(args, body):
        d = _disk(args)
        if args.get("restore"):
            d.restore()
        if "latency" in args:
            d.set_latency(args["latency"])
        if "flaky_for" in args:
            d.set_flaky(args["flaky_for"])
        if args.get("lose"):
            d.lose()
        return d.status()

    def status(args, body):
        return {drive: d.status() for drive, d in chaos_disks.items()}

    router.register("chaos.inject", inject)
    router.register("chaos.status", status)
