"""Per-drive storage interface.

Capability-equivalent of the reference's 35-method StorageAPI
(cmd/storage-interface.go:27): volume ops, streaming shard file IO,
version-aware metadata ops, atomic rename-into-place, sorted dir walking,
and bitrot verification.  Implementations: LocalStorage (POSIX dirs,
storage/local.py) and RemoteStorage (HTTP RPC, distributed plane).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import BinaryIO, Iterator

from .xlmeta import FileInfo


@dataclass
class DiskInfo:
    total: int = 0
    free: int = 0
    used: int = 0
    fs_type: str = ""
    root_disk: bool = False
    healing: bool = False
    endpoint: str = ""
    mount_path: str = ""
    id: str = ""
    error: str = ""
    metrics: dict = field(default_factory=dict)


@dataclass
class VolInfo:
    name: str
    created: float


class StorageAPI(abc.ABC):
    """One drive (local directory or remote peer drive)."""

    # -- identity / health --------------------------------------------------
    @abc.abstractmethod
    def disk_id(self) -> str: ...

    @abc.abstractmethod
    def set_disk_id(self, disk_id: str) -> None: ...

    @abc.abstractmethod
    def is_online(self) -> bool: ...

    @abc.abstractmethod
    def disk_info(self) -> DiskInfo: ...

    def is_local(self) -> bool:
        return True

    def endpoint(self) -> str:
        return ""

    def close(self) -> None:
        pass

    # -- volumes ------------------------------------------------------------
    @abc.abstractmethod
    def make_volume(self, volume: str) -> None: ...

    @abc.abstractmethod
    def list_volumes(self) -> list[VolInfo]: ...

    @abc.abstractmethod
    def stat_volume(self, volume: str) -> VolInfo: ...

    @abc.abstractmethod
    def delete_volume(self, volume: str, force: bool = False) -> None: ...

    # -- flat files ---------------------------------------------------------
    @abc.abstractmethod
    def read_all(self, volume: str, path: str) -> bytes: ...

    @abc.abstractmethod
    def write_all(self, volume: str, path: str, data: bytes) -> None:
        """Write a small flat file atomically (stage to a tmp name,
        rename into place)."""

    @abc.abstractmethod
    def delete(self, volume: str, path: str, recursive: bool = False) -> None: ...

    @abc.abstractmethod
    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None: ...

    # -- shard files --------------------------------------------------------
    @abc.abstractmethod
    def create_file(self, volume: str, path: str, size: int,
                    reader: BinaryIO) -> None: ...

    @abc.abstractmethod
    def open_file_writer(self, volume: str, path: str,
                         size_hint: int = -1) -> BinaryIO:
        """Streaming writer handle (closed by caller).  `size_hint` is
        the expected final size when known (-1 unknown): implementations
        may use it to pick a write strategy (buffered vs O_DIRECT)."""

    @abc.abstractmethod
    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int) -> BinaryIO: ...

    @abc.abstractmethod
    def read_file(self, volume: str, path: str, offset: int,
                  buf_size: int) -> bytes: ...

    # -- object metadata ----------------------------------------------------
    @abc.abstractmethod
    def read_version(self, volume: str, path: str, version_id: str = "",
                     read_data: bool = False) -> FileInfo: ...

    @abc.abstractmethod
    def read_xl(self, volume: str, path: str) -> bytes: ...

    @abc.abstractmethod
    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def delete_version(self, volume: str, path: str, fi: FileInfo,
                       force_del_marker: bool = False) -> None: ...

    @abc.abstractmethod
    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None: ...

    # -- listing / verification ---------------------------------------------
    @abc.abstractmethod
    def list_dir(self, volume: str, path: str, count: int = -1) -> list[str]: ...

    @abc.abstractmethod
    def walk_dir(self, volume: str, base: str = "",
                 recursive: bool = True) -> Iterator[str]:
        """Yield object names (entries holding xl.meta) in sorted order
        (reference WalkDir, cmd/metacache-walk.go:62)."""

    @abc.abstractmethod
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Full bitrot verification of this drive's shard of every part
        (reference VerifyFile, cmd/xl-storage.go:2341)."""

    @abc.abstractmethod
    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        """Cheap existence/size check of part files (CheckParts)."""
