"""Drive hardware diagnostics: mount resolution + SMART-ish identity.

Reference: internal/mountinfo (mountinfo_linux.go — CheckCrossDevice,
detecting multiple drives that actually share one filesystem) and
internal/smart (device model / rotational identity surfaced in admin
storage info).  Pure /proc + /sys readers: no ioctls, no external
tools, graceful None on non-Linux or containerized environments where
the block layer is hidden.
"""

from __future__ import annotations

import os


def _read(path: str) -> str | None:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            return f.read().strip()
    except OSError:
        return None


def _mounts() -> list[tuple[str, str, str]]:
    """[(mount_point, source_device, fstype)] from /proc/self/mountinfo
    (escape sequences like \\040 decoded)."""
    out = []
    try:
        with open("/proc/self/mountinfo", encoding="utf-8") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 10 or "-" not in parts:
                    continue
                dash = parts.index("-")
                mp = parts[4].encode().decode("unicode_escape")
                fstype = parts[dash + 1]
                src = parts[dash + 2]
                out.append((mp, src, fstype))
    except OSError:
        pass
    return out


def mount_of(path: str, mounts=None) -> tuple[str, str, str]:
    """-> (mount_point, source_device, fstype) of the longest-prefix
    mount covering `path` ("", "", "") when unresolvable.  Pass a
    pre-parsed `mounts` list when resolving many paths — re-reading
    /proc/self/mountinfo per drive is pointless work."""
    real = os.path.realpath(path)
    best = ("", "", "")
    best_len = -1
    for mp, src, fstype in (mounts if mounts is not None else _mounts()):
        if (real == mp or real.startswith(mp.rstrip("/") + "/")
                or mp == "/") and len(mp) > best_len:
            best = (mp, src, fstype)
            best_len = len(mp)
    return best


def _block_parent(dev: str) -> str:
    """Partition -> parent disk name (sda1 -> sda, nvme0n1p2 ->
    nvme0n1) via /sys/class/block symlinks; unchanged when already a
    whole disk or unresolvable."""
    link = f"/sys/class/block/{dev}"
    try:
        target = os.path.realpath(link)
        parent = os.path.basename(os.path.dirname(target))
        if parent and os.path.exists(f"/sys/block/{parent}"):
            return parent
    except OSError:
        pass
    return dev


def drive_hardware(path: str, mounts=None) -> dict:
    """Best-effort per-drive hardware identity for admin storage info:
    mountPoint/fsType always (Linux), rotational/model/device when the
    block device is visible."""
    mp, src, fstype = mount_of(path, mounts)
    info: dict = {"mountPoint": mp, "fsType": fstype}
    dev = os.path.basename(src) if src.startswith("/dev/") else ""
    if dev:
        disk = _block_parent(dev)
        info["device"] = src
        rot = _read(f"/sys/block/{disk}/queue/rotational")
        if rot is not None:
            info["rotational"] = rot == "1"
        model = _read(f"/sys/block/{disk}/device/model")
        if model:
            info["model"] = model
    return info


def shared_mount_warnings(paths: list[str], mounts=None) -> list[str]:
    """Drives configured as separate endpoints but living on ONE
    filesystem give no fault isolation and mis-count capacity — the
    reference refuses such layouts (mountinfo_linux.go
    CheckCrossDevice); we surface loud warnings in admin info."""
    by_fs: dict[tuple, list[str]] = {}
    for p in paths:
        try:
            st = os.stat(p)
        except OSError:
            continue
        by_fs.setdefault((st.st_dev,), []).append(p)
    warnings = []
    for key, group in sorted(by_fs.items()):
        if len(group) > 1:
            mp, _, _ = mount_of(group[0], mounts)
            warnings.append(
                f"drives {', '.join(sorted(group))} share one "
                f"filesystem (mount {mp or 'unknown'}): no fault "
                "isolation between them")
    return warnings
