"""Local POSIX drive implementation.

Equivalent of the reference's xlStorage (cmd/xl-storage.go:90): one
directory per drive, objects stored as
    <drive>/<bucket>/<object>/xl.meta
    <drive>/<bucket>/<object>/<data_dir>/part.N
with a `.minio_tpu.sys` system volume for tmp staging, multipart state and
drive metadata (format.json, healing tracker).  Writes stage into tmp and
move into place with atomic renames (reference RenameData,
cmd/xl-storage.go:1964).
"""

from __future__ import annotations

import io
import json
import os
import shutil
import threading
import time
import uuid
from typing import BinaryIO, Iterator

import numpy as _np

from minio_tpu.utils.deadline import service_thread

from . import errors, metajournal
from .api import DiskInfo, StorageAPI, VolInfo
from .xlmeta import NULL_VERSION_ID, FileInfo, XLMeta, file_info_from_raw

SYSTEM_VOL = ".minio_tpu.sys"
TMP_DIR = "tmp"
XL_META_FILE = "xl.meta"
FORMAT_FILE = "format.json"
HEALING_FILE = ".healing.bin"

# Durability: fdatasync files before commit renames and fsync parent dirs
# after, so an ACKed write survives power loss (reference fdatasync usage,
# cmd/xl-storage.go:1667 + internal/disk/fdatasync_linux.go:40).  Tests
# disable via MINIO_TPU_FSYNC=0 for speed; production default is on.
FSYNC_ENABLED = os.environ.get("MINIO_TPU_FSYNC", "1").lower() not in (
    "0", "off", "false")

# O_DIRECT streaming for shard files: bulk data bypasses the page cache so
# a storage node's RAM stays available for caches that matter (metacache,
# usage) and write throughput is the drive's, not the flush daemon's
# (reference cmd/xl-storage.go:1667 CreateFile / :1558 ReadFileStream via
# odirectReader + internal/disk/directio_unix.go:27-50).  Filesystems
# without O_DIRECT (tmpfs) fall back to buffered IO per drive,
# automatically.
ODIRECT_ENABLED = os.environ.get("MINIO_TPU_ODIRECT", "1").lower() not in (
    "0", "off", "false") and hasattr(os, "O_DIRECT")
_ALIGN = 4096          # logical block alignment O_DIRECT demands
_DIO_BUF = 1 << 20     # aligned staging-buffer size
# files smaller than this are written buffered even when O_DIRECT is on:
# a sub-1MiB shard never fills the aligned staging buffer, so the whole
# file goes out through the drop-O_DIRECT tail path anyway — paying the
# mmap/fcntl setup for nothing (the reference gates odirect behind a
# small-file threshold the same way, cmd/xl-storage.go CreateFile)
ODIRECT_MIN_BYTES = int(os.environ.get(
    "MINIO_TPU_ODIRECT_MIN_BYTES", str(1 << 20)))
# concurrent O_DIRECT device writes allowed across ALL drives of this
# process: synchronous direct writes contend at the backing device, and
# past a small fan-in aggregate bandwidth DEGRADES (measured here:
# 2-way 1.7 GiB/s vs 12-way 0.89 GiB/s on one backing device).  Default
# scales with cores — a many-core storage server with real independent
# drives effectively disables the gate; single-device sandboxes get the
# optimal small fan-in.  0 disables.
DEVICE_WRITE_CONCURRENCY = int(os.environ.get(
    "MINIO_TPU_DEVICE_WRITE_CONCURRENCY",
    str(max(2, os.cpu_count() or 2))))
_device_write_gate = (
    threading.BoundedSemaphore(DEVICE_WRITE_CONCURRENCY)
    if DEVICE_WRITE_CONCURRENCY > 0 else None)
# longest a flush waits for a gate slot before writing ungated: slots
# held by writes to a hung drive must not fence healthy drives
_GATE_WAIT_S = float(os.environ.get(
    "MINIO_TPU_DEVICE_WRITE_GATE_WAIT_S", "2.0"))
TRASH_DIR = "trash"

# Reusable page-aligned staging buffers for _DirectWriter: every PUT
# opens one writer per drive, and a fresh mmap + munmap per writer is
# measurable syscall/page-fault churn on the hot path.
_staging_lock = threading.Lock()
_staging_pool: list = []
_STAGING_POOL_MAX = 16


def _staging_acquire():
    import mmap

    with _staging_lock:
        if _staging_pool:
            return _staging_pool.pop()
    return mmap.mmap(-1, _DIO_BUF)


def _staging_release(buf) -> None:
    with _staging_lock:
        if len(_staging_pool) < _STAGING_POOL_MAX:
            _staging_pool.append(buf)
            return
    buf.close()


def _fdatasync(fileobj) -> None:
    if not FSYNC_ENABLED:
        return
    fileobj.flush()
    if hasattr(os, "fdatasync"):
        os.fdatasync(fileobj.fileno())
    else:  # pragma: no cover - non-linux
        os.fsync(fileobj.fileno())


def _fsync_dir(path: str) -> None:
    """Persist a directory entry (the rename itself) to disk."""
    if not FSYNC_ENABLED:
        return
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _SyncedWriter:
    """File wrapper that fdatasyncs on close, so shard bytes are durable
    before the commit rename publishes them."""

    def __init__(self, f):
        self._f = f

    def write(self, data) -> int:
        return self._f.write(data)

    def flush(self) -> None:
        self._f.flush()

    def fileno(self) -> int:
        return self._f.fileno()

    def close(self) -> None:
        if not self._f.closed:
            _fdatasync(self._f)
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False


def _disable_direct(fd: int) -> None:
    """Drop O_DIRECT from an open fd (for the unaligned tail — reference
    disableDirectIO, internal/disk/directio_unix.go:40)."""
    import fcntl

    flags = fcntl.fcntl(fd, fcntl.F_GETFL)
    fcntl.fcntl(fd, fcntl.F_SETFL, flags & ~os.O_DIRECT)


class _DirectWriter:
    """Sequential O_DIRECT writer: data accumulates in a page-aligned
    staging buffer and is written in aligned 1 MiB bursts; the unaligned
    tail is written after dropping O_DIRECT at close (the reference's
    odirectWriter tail handling, cmd/xl-storage.go:1667).  On the first
    EINVAL (filesystem without O_DIRECT) the writer downgrades itself
    and reports it via `storage`, so the drive stops trying."""

    #: bitrot write_frames hint: per-row write() calls land in the
    #: aligned staging buffer anyway, so row-wise feeding skips the
    #: interleaved-frame materialization pass (cheap calls, same bytes)
    prefers_row_writes = True

    def __init__(self, path: str, storage: "LocalStorage"):
        self._storage = storage
        self._fd = os.open(path,
                           os.O_WRONLY | os.O_CREAT | os.O_TRUNC
                           | os.O_DIRECT, 0o644)
        self._buf = _staging_acquire()
        self._view = memoryview(self._buf)
        # numpy view for staging copies: large contiguous numpy copies
        # release the GIL (memoryview slice assignment does not), so a
        # 12-drive shard fan-out's staging memcpys overlap instead of
        # convoying the interpreter
        self._np = _np.frombuffer(self._buf, dtype=_np.uint8)
        self._fill = 0
        self._direct = True
        self._closed = False

    def write(self, data) -> int:
        src = _np.frombuffer(
            data if isinstance(data, (bytes, bytearray)) else
            memoryview(data).cast("B"), dtype=_np.uint8)
        total = src.size
        pos = 0
        while pos < total:
            n = min(_DIO_BUF - self._fill, total - pos)
            self._np[self._fill:self._fill + n] = src[pos:pos + n]
            self._fill += n
            pos += n
            if self._fill == _DIO_BUF:
                self._flush_aligned(_DIO_BUF)
        return total

    def _flush_aligned(self, nbytes: int) -> None:
        done = 0
        gate = _device_write_gate
        held = False
        if gate is not None:
            # bounded wait: the gate is a throughput optimization, not a
            # correctness fence — a slot pinned by a write to a hung
            # drive (os.write to D-state storage ignores deadlines) must
            # not stall healthy drives' flushes, or one dead device
            # blocks write quorum across the whole node
            held = gate.acquire(timeout=_GATE_WAIT_S)
        try:
            while done < nbytes:
                try:
                    done += os.write(self._fd, self._view[done:nbytes])
                except OSError as e:
                    import errno

                    if self._direct and e.errno == errno.EINVAL:
                        # filesystem rejected direct IO: downgrade this
                        # fd and remember per drive
                        _disable_direct(self._fd)
                        self._direct = False
                        self._storage._odirect = False
                        continue
                    raise
        finally:
            if held:
                gate.release()
        self._fill -= nbytes
        if self._fill:
            self._view[:self._fill] = self._view[nbytes:nbytes + self._fill]

    def flush(self) -> None:
        """No-op: alignment forbids partial flushes; close() drains."""

    # no fileno(): raw-fd fast paths (the bitrot writev gather) would
    # bypass the aligned staging buffer and EINVAL on the O_DIRECT fd —
    # their AttributeError fallback routes bytes through write() instead

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            aligned = (self._fill // _ALIGN) * _ALIGN
            if aligned:
                self._flush_aligned(aligned)
            if self._fill:
                if self._direct:
                    _disable_direct(self._fd)
                done = 0
                while done < self._fill:
                    done += os.write(self._fd, self._view[done:self._fill])
                self._fill = 0
            if FSYNC_ENABLED:
                if hasattr(os, "fdatasync"):
                    os.fdatasync(self._fd)
                else:  # pragma: no cover - non-linux
                    os.fsync(self._fd)
        finally:
            os.close(self._fd)
            self._np = None  # drop the buffer export before pooling
            self._view.release()
            _staging_release(self._buf)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False


class _DirectReader:
    """Sequential O_DIRECT reader from offset 0: refills a page-aligned
    1 MiB buffer with os.readv and serves arbitrary read() sizes from it
    (reference odirectReader, cmd/xl-storage.go:1558).  The final short
    read at an unaligned EOF is legal under O_DIRECT."""

    def __init__(self, path: str):
        import stat as stat_mod

        self._fd = os.open(path, os.O_RDONLY | os.O_DIRECT)
        if stat_mod.S_ISDIR(os.fstat(self._fd).st_mode):
            os.close(self._fd)
            raise IsADirectoryError(path)
        self._buf = _staging_acquire()
        self._have = 0     # valid bytes in buffer
        self._pos = 0      # consumed bytes in buffer
        self._buf_off = 0  # file offset of the buffer's first byte
        self._next_off = 0  # file offset of the next readv
        self._eof = False
        self._final = False
        self._closed = False

    def _refill(self) -> None:
        if self._eof:
            return
        if self._final:
            # a short O_DIRECT read only happens at EOF; another readv
            # would run from an unaligned offset
            self._eof = True
            return
        self._pos = 0
        self._buf_off = self._next_off
        self._have = os.readv(self._fd, [self._buf])
        self._next_off += self._have
        if self._have == 0:
            self._eof = True
        elif self._have < _DIO_BUF:
            self._final = True

    def seek(self, target: int, whence: int = 0) -> int:
        """Absolute seeks only (the shard read path positions to frame
        boundaries); re-reads from the preceding aligned offset so the
        fd's O_DIRECT alignment is preserved."""
        if whence != 0:
            raise OSError("O_DIRECT reader supports absolute seek only")
        if self._buf_off <= target <= self._buf_off + self._have:
            self._pos = target - self._buf_off
            self._eof = False
            return target
        aligned = (target // _ALIGN) * _ALIGN
        os.lseek(self._fd, aligned, os.SEEK_SET)
        self._next_off = aligned
        self._have = self._pos = 0
        self._buf_off = aligned
        self._eof = self._final = False
        skip = target - aligned
        if skip:
            self._refill()
            self._pos = min(skip, self._have)
        return target

    def tell(self) -> int:
        return self._buf_off + self._pos

    def read(self, n: int = -1) -> bytes:
        out = []
        want = n if n >= 0 else None
        while want is None or want > 0:
            if self._pos == self._have:
                self._refill()
                if self._eof:
                    break
            take = self._have - self._pos if want is None \
                else min(want, self._have - self._pos)
            out.append(self._buf[self._pos:self._pos + take])
            self._pos += take
            if want is not None:
                want -= take
        return b"".join(out)

    def readinto(self, b) -> int:
        """Fill a caller-provided buffer straight from the aligned
        staging buffer — the bitrot frame reader preallocates its frame
        group and pulls it here in ONE copy (read() would slice + join,
        an extra pass per group)."""
        mv = memoryview(b)
        if mv.format != "B":
            mv = mv.cast("B")
        src = memoryview(self._buf)
        got = 0
        while got < len(mv):
            if self._pos == self._have:
                self._refill()
                if self._eof:
                    break
            take = min(len(mv) - got, self._have - self._pos)
            mv[got:got + take] = src[self._pos:self._pos + take]
            self._pos += take
            got += take
        return got

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            os.close(self._fd)
            _staging_release(self._buf)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False


def _stored_algo(fi: FileInfo) -> str:
    """Bitrot algorithm a version's shards were written with."""
    from minio_tpu.erasure import bitrot

    e = fi.erasure
    if e is not None and e.checksums:
        a = e.checksums[0].algorithm
        if a in bitrot.ALGORITHMS:
            return a
    return bitrot.DEFAULT_ALGO


def _clean(path: str) -> str:
    path = path.strip("/")
    if ".." in path.split("/"):
        raise errors.FileAccessDenied(path)
    return path


class LocalStorage(StorageAPI):
    def __init__(self, root: str, endpoint: str = "", quota: int | None = None):
        self.root = os.path.abspath(root)
        self._endpoint = endpoint or self.root
        self._disk_id = ""
        # staged files written unsynced (append_file) pending a commit sync
        self._unsynced: set[str] = set()
        self._lock = threading.Lock()
        # optional per-drive capacity cap: disk_info reports
        # total=quota / free=quota-used so pool placement (weighted by
        # available space, cmd/erasure-server-pool.go:222) works on
        # shared filesystems where statvfs can't tell drives apart
        if quota is None:
            quota = int(os.environ.get("MINIO_TPU_DRIVE_QUOTA", "0") or 0)
        self._quota = max(quota, 0)
        self._du_cache: tuple[float, int] = (0.0, 0)
        self._odirect = ODIRECT_ENABLED
        self._reaper: threading.Thread | None = None
        os.makedirs(self.root, exist_ok=True)
        os.makedirs(os.path.join(self.root, SYSTEM_VOL, TMP_DIR), exist_ok=True)
        # xl.meta commit journal (ISSUE 17): replay a leftover journal
        # unconditionally — a crashed journal-on process followed by a
        # journal-off one must still recover its acked commits and must
        # not leave a stale journal behind to clobber newer writes
        self._journal: metajournal.MetaJournal | None = None
        self._index_stale = False  # journal-off invalidation, once
        if metajournal.JOURNAL_ENABLED:
            self._journal = metajournal.MetaJournal(
                self.root, self._apply_xl_raw, self._apply_unlink_raw,
                list_names=self._walk_names, fsync=FSYNC_ENABLED)
            self._meta_index = self._journal.index
        else:
            metajournal.startup_replay(
                self.root, self._apply_xl_raw, self._apply_unlink_raw,
                fsync=FSYNC_ENABLED)
            # read-only index view: still serves listings if this
            # process never mutates metadata (first mutation drops the
            # VALID marker)
            self._meta_index = metajournal.MetaIndex(
                self.root, fsync=FSYNC_ENABLED)
        # reap trash a previous process left behind (crash mid-reap)
        trash = os.path.join(self.root, SYSTEM_VOL, TRASH_DIR)
        if os.path.isdir(trash) and os.listdir(trash):
            self._kick_reaper()

    # -- trash (non-blocking deletes) ---------------------------------------
    def _move_to_trash(self, path: str) -> bool:
        """Rename a file/dir into the trash for background reaping — the
        request path pays one rename, not an rmtree (reference
        moveToTrash, cmd/xl-storage.go:950).  False -> caller deletes
        inline."""
        trash = self._sys_path(TRASH_DIR)
        try:
            os.makedirs(trash, exist_ok=True)
            os.replace(path, os.path.join(trash, uuid.uuid4().hex))
        except OSError:
            return False
        self._kick_reaper()
        return True

    def _kick_reaper(self) -> None:
        """Reaper thread runs until the trash is empty, then exits (no
        idle thread per drive; the next trashed item respawns it)."""
        with self._lock:
            if self._reaper is not None and self._reaper.is_alive():
                return
            t = service_thread(self._reap_loop, start=False,
                               name=f"trash-reaper:{self.root}")
            self._reaper = t
        t.start()

    def _reap_loop(self) -> None:
        trash = self._sys_path(TRASH_DIR)
        while True:
            try:
                entries = os.listdir(trash)
            except OSError:
                entries = []
            if not entries:
                # re-check under the lock so a rename that raced the
                # empty listing still gets a live reaper
                with self._lock:
                    try:
                        if not os.listdir(trash):
                            self._reaper = None
                            return
                    except OSError:
                        self._reaper = None
                        return
                continue
            for name in entries:
                p = os.path.join(trash, name)
                try:
                    if os.path.isdir(p):
                        shutil.rmtree(p, ignore_errors=True)
                    else:
                        os.remove(p)
                except OSError:
                    pass

    def _discard_dir(self, path: str) -> None:
        """Reclaim a data dir without blocking the request path."""
        if os.path.isdir(path):
            if not self._move_to_trash(path):
                shutil.rmtree(path, ignore_errors=True)

    def wait_trash_empty(self, timeout: float = 10.0) -> bool:
        """Test/maintenance hook: block until the reaper drains."""
        deadline = time.time() + timeout
        trash = self._sys_path(TRASH_DIR)
        while time.time() < deadline:
            try:
                if not os.listdir(trash):
                    return True
            except OSError:
                return True
            time.sleep(0.02)
        return False

    # -- identity -----------------------------------------------------------
    def disk_id(self) -> str:
        return self._disk_id

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id

    def is_online(self) -> bool:
        return os.path.isdir(self.root)

    def endpoint(self) -> str:
        return self._endpoint

    def _used_bytes(self) -> int:
        """Bytes stored under this drive root (0.5 s TTL cache: the pool
        placement probe hits this on every PUT)."""
        now = time.monotonic()
        ts, used = self._du_cache
        if now - ts < 0.5:
            return used
        used = 0
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                try:
                    used += os.lstat(os.path.join(dirpath, f)).st_size
                except OSError:
                    pass
        self._du_cache = (now, used)
        return used

    def invalidate_usage_cache(self) -> None:
        """Force the next disk_info() to re-measure (rebalance rounds
        steer by used bytes and must not see the 0.5 s-stale value)."""
        self._du_cache = (0.0, 0)

    def disk_info(self) -> DiskInfo:
        st = shutil.disk_usage(self.root)
        total, free, used = st.total, st.free, st.used
        if self._quota:
            du = self._used_bytes()
            total = self._quota
            used = min(du, self._quota)
            free = min(max(self._quota - du, 0), st.free)
        return DiskInfo(
            total=total, free=free, used=used,
            healing=os.path.exists(self._sys_path(HEALING_FILE)),
            endpoint=self._endpoint, mount_path=self.root, id=self._disk_id,
        )

    def _sys_path(self, *parts: str) -> str:
        return os.path.join(self.root, SYSTEM_VOL, *parts)

    # -- path helpers -------------------------------------------------------
    def _vol_path(self, volume: str) -> str:
        if not volume:
            raise errors.InvalidArgument("empty volume")
        return os.path.join(self.root, volume)

    def _file_path(self, volume: str, path: str) -> str:
        return os.path.join(self._vol_path(volume), _clean(path))

    # -- volumes ------------------------------------------------------------
    def make_volume(self, volume: str) -> None:
        p = self._vol_path(volume)
        if os.path.isdir(p):
            raise errors.VolumeExists(volume)
        os.makedirs(p, exist_ok=True)

    def list_volumes(self) -> list[VolInfo]:
        out = []
        for name in sorted(os.listdir(self.root)):
            p = os.path.join(self.root, name)
            if os.path.isdir(p) and name != SYSTEM_VOL:
                out.append(VolInfo(name=name, created=os.stat(p).st_ctime))
        return out

    def stat_volume(self, volume: str) -> VolInfo:
        p = self._vol_path(volume)
        if not os.path.isdir(p):
            raise errors.VolumeNotFound(volume)
        return VolInfo(name=volume, created=os.stat(p).st_ctime)

    def delete_volume(self, volume: str, force: bool = False) -> None:
        p = self._vol_path(volume)
        if not os.path.isdir(p):
            raise errors.VolumeNotFound(volume)
        if force and volume != SYSTEM_VOL and self._journal is not None:
            # durable tombstone BEFORE the dir goes: a crash mid-delete
            # replays the tombstone instead of resurrecting journaled
            # objects of the dead bucket (the tombstone also drops the
            # bucket's index inside the committer).  Only the force
            # path journals — a failed non-force rmdir must not leave a
            # tombstone that would rmtree a live bucket on replay.
            try:
                self._journal.bucket_delete(volume)
            except metajournal.JournalDead:
                self._mark_index_stale()
                self._meta_index.drop_bucket(volume)
        elif volume != SYSTEM_VOL:
            # the bucket's index dies with it (segments would otherwise
            # resurrect its names if the bucket is recreated)
            self._meta_index.drop_bucket(volume)
        if force:
            if not self._move_to_trash(p):
                shutil.rmtree(p, ignore_errors=True)
            return
        try:
            os.rmdir(p)
        except OSError:
            raise errors.BucketNotEmpty(volume)

    # -- flat files ---------------------------------------------------------
    def read_all(self, volume: str, path: str) -> bytes:
        p = self._file_path(volume, path)
        try:
            with open(p, "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise errors.FileNotFound(f"{volume}/{path}")
        except IsADirectoryError:
            raise errors.FileNotFound(f"{volume}/{path}")

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        p = self._file_path(volume, path)
        target = p + f".tmp.{uuid.uuid4().hex[:8]}"
        for attempt in (0, 1):
            try:
                # try-first: parent usually exists; makedirs after a miss
                f = open(target, "wb")
                break
            except FileNotFoundError:
                if attempt:
                    raise
                self._ensure_parent(p)
        with f:
            f.write(data)
            _fdatasync(f)
        os.replace(target, p)
        _fsync_dir(os.path.dirname(p))

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        p = self._file_path(volume, path)
        try:
            if os.path.isdir(p):
                if recursive:
                    try:
                        # empty dir (drained multipart staging, cleaned
                        # tmp): plain rmdir — a trash rename would spin
                        # up a reaper thread for nothing
                        os.rmdir(p)
                    except OSError:
                        # one rename; the reaper does the rmtree off the
                        # request path (moveToTrash, cmd/xl-storage.go:950)
                        if not self._move_to_trash(p):
                            shutil.rmtree(p)
                else:
                    os.rmdir(p)
            else:
                os.remove(p)
        except FileNotFoundError:
            raise errors.FileNotFound(f"{volume}/{path}")
        # prune now-empty parents up to the volume root.  Structural
        # system dirs (tmp staging, trash) are never pruned: concurrent
        # writers makedirs+create under them, and a prune racing that
        # walk turns a parallel multipart commit into FileNotFoundError
        parent = os.path.dirname(p)
        vol_root = self._vol_path(volume)
        keep = {vol_root}
        if volume == SYSTEM_VOL:
            keep.add(os.path.join(vol_root, TMP_DIR))
            keep.add(os.path.join(vol_root, TRASH_DIR))
        while parent not in keep and parent.startswith(vol_root):
            try:
                os.rmdir(parent)
            except OSError:
                break
            parent = os.path.dirname(parent)

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        src = self._file_path(src_volume, src_path)
        dst = self._file_path(dst_volume, dst_path)
        try:
            # try-first: one syscall on the hot path; the pre-stat +
            # makedirs walk only runs after a miss
            os.replace(src, dst)
        except FileNotFoundError:
            if not os.path.exists(src):
                raise errors.FileNotFound(f"{src_volume}/{src_path}")
            self._ensure_parent(dst)
            try:
                os.replace(src, dst)
            except FileNotFoundError:
                raise errors.FileNotFound(f"{src_volume}/{src_path}")
        _fsync_dir(os.path.dirname(dst))

    # -- shard files --------------------------------------------------------
    def create_file(self, volume: str, path: str, size: int,
                    reader: BinaryIO) -> None:
        with self.open_file_writer(volume, path) as w:
            remaining = size if size >= 0 else None
            while True:
                chunk = reader.read(1 << 20)
                if not chunk:
                    break
                w.write(chunk)
                if remaining is not None:
                    remaining -= len(chunk)
                    if remaining <= 0:
                        break

    @staticmethod
    def _ensure_parent(p: str) -> None:
        """makedirs that tolerates a concurrent empty-parent prune: a
        delete() on a sibling can rmdir an intermediate dir between our
        walk and our mkdir — re-walk instead of failing the writer."""
        for attempt in range(3):
            try:
                os.makedirs(os.path.dirname(p), exist_ok=True)
                return
            except FileNotFoundError:
                if attempt == 2:
                    raise

    def open_file_writer(self, volume: str, path: str,
                         size_hint: int = -1) -> BinaryIO:
        """`size_hint` >= 0 is the expected file size: small files skip
        O_DIRECT (they would ride the unaligned-tail fallback anyway and
        the buffered writer keeps the writev gather fast path)."""
        p = self._file_path(volume, path)
        # try-first: the parent almost always exists (upload dirs, tmp)
        # and fs metadata ops are the multipart hot path — only walk
        # makedirs after a miss
        for attempt in (0, 1):
            try:
                if self._odirect and not 0 <= size_hint < ODIRECT_MIN_BYTES:
                    try:
                        return _DirectWriter(p, self)
                    except FileNotFoundError:
                        raise
                    except OSError:
                        self._odirect = False  # fs rejected O_DIRECT
                return _SyncedWriter(open(p, "wb"))
            except FileNotFoundError:
                if attempt:
                    raise
                self._ensure_parent(p)

    def append_file(self, volume: str, path: str, data: bytes,
                    append: bool = True) -> None:
        """Append (or truncate-then-write) a chunk; the remote shard-stream
        protocol's write primitive (reference AppendFile,
        cmd/xl-storage.go).  Not synced per-chunk: the path is recorded so
        rename_data fdatasyncs it once at commit."""
        p = self._file_path(volume, path)
        self._ensure_parent(p)
        with open(p, "ab" if append else "wb") as f:
            f.write(data)
        self._unsynced.add(p)

    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int) -> BinaryIO:
        p = self._file_path(volume, path)
        if offset == 0 and self._odirect:
            # whole-file sequential reads ride O_DIRECT (reference
            # odirectReader for offset 0, cmd/xl-storage.go:1558);
            # ranged reads stay buffered — their offsets are unaligned
            try:
                f = _DirectReader(p)
            except FileNotFoundError:
                raise errors.FileNotFound(f"{volume}/{path}")
            except IsADirectoryError:
                raise errors.FileNotFound(f"{volume}/{path}")
            except OSError:
                self._odirect = False
            else:
                if length >= 0:
                    size = os.fstat(f._fd).st_size
                    if size < length:
                        f.close()
                        raise errors.FileCorrupt(
                            f"{volume}/{path}: size {size} < {length}")
                return f
        try:
            f = open(p, "rb")
        except FileNotFoundError:
            raise errors.FileNotFound(f"{volume}/{path}")
        except IsADirectoryError:
            raise errors.FileNotFound(f"{volume}/{path}")
        if length >= 0:
            st = os.fstat(f.fileno())
            if st.st_size < offset + length:
                f.close()
                raise errors.FileCorrupt(
                    f"{volume}/{path}: size {st.st_size} < {offset + length}"
                )
        f.seek(offset)
        return f

    def read_file(self, volume: str, path: str, offset: int,
                  buf_size: int) -> bytes:
        with self.read_file_stream(volume, path, offset, buf_size) as f:
            return f.read(buf_size)

    # -- object metadata ----------------------------------------------------
    def _meta_path(self, volume: str, path: str) -> str:
        return os.path.join(self._file_path(volume, path), XL_META_FILE)

    def read_xl(self, volume: str, path: str) -> bytes:
        try:
            with open(self._meta_path(volume, path), "rb") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError):
            raise errors.FileNotFound(f"{volume}/{path}")

    def read_version(self, volume: str, path: str, version_id: str = "",
                     read_data: bool = False) -> FileInfo:
        raw = self.read_xl(volume, path)
        fi = file_info_from_raw(raw, volume, path, version_id, read_data)
        return fi

    # -- journal plumbing (ISSUE 17) ----------------------------------------
    def _apply_xl_raw(self, bucket: str, path: str, data: bytes) -> None:
        """Buffered xl.meta apply (tmp+rename, NO sync): durability is
        the journal's group fsync; rotation/replay sync the file.

        Hot-path economies (the committer is the ONLY caller, plus the
        single-threaded startup replay, so one reusable tmp name under
        the sys dir is race-free): no per-write uuid tmp, and makedirs
        only on the ENOENT fallback — the target dir almost always
        exists.  os.replace is atomic across dirs on the same fs, the
        same .minio.sys/tmp -> bucket rename MinIO itself does."""
        p = self._meta_path(bucket, path)
        tmp = os.path.join(self.root, SYSTEM_VOL, "xl-apply.tmp")
        flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
        try:
            fd = os.open(tmp, flags, 0o644)
        except FileNotFoundError:
            os.makedirs(os.path.dirname(tmp), exist_ok=True)
            fd = os.open(tmp, flags, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        try:
            os.replace(tmp, p)
        except FileNotFoundError:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            os.replace(tmp, p)

    def _apply_unlink_raw(self, bucket: str, path: str) -> None:
        """Idempotent object-dir removal for journal apply/replay."""
        try:
            self.delete(bucket, path, recursive=True)
        except errors.FileNotFound:
            pass  # replayed unlink already applied

    def _walk_names(self, bucket: str):
        """Name stream for background index seeding."""
        return self.walk_dir(bucket)

    def _mark_index_stale(self) -> None:
        """Journal-off metadata mutation: the on-disk index can no
        longer trust itself (one unlink, then a cached flag)."""
        if not self._index_stale:
            self._index_stale = True
            self._meta_index.invalidate()

    def index_names(self, bucket: str, prefix: str = "",
                    marker: str = "") -> list[str] | None:
        """Sorted live object names from the metadata index, or None
        when the index can't serve this bucket (caller walks)."""
        if bucket == SYSTEM_VOL:
            return None
        try:
            return self._meta_index.names(bucket, prefix, marker)
        except Exception:
            return None

    def index_available(self, bucket: str) -> bool:
        return bucket != SYSTEM_VOL and self._meta_index.is_valid() \
            and self._meta_index.bucket_seeded(bucket)

    def _write_xl(self, volume: str, path: str, xl: XLMeta) -> None:
        if self._journal is not None and volume != SYSTEM_VOL:
            try:
                # blocks until the group fsync lands AND the buffered
                # xl.meta rename is visible (read-your-writes)
                self._journal.commit(volume, _clean(path), xl.dumps())
                return
            except metajournal.JournalDead:
                pass  # committer gone: fall through to the synced path
        if volume != SYSTEM_VOL:
            self._mark_index_stale()
        p = self._meta_path(volume, path)
        tmp = p + f".tmp.{uuid.uuid4().hex[:8]}"
        flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
        try:
            fd = os.open(tmp, flags, 0o644)
        except FileNotFoundError:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            fd = os.open(tmp, flags, 0o644)
        try:
            os.write(fd, xl.dumps())
            if FSYNC_ENABLED:
                if hasattr(os, "fdatasync"):
                    os.fdatasync(fd)
                else:  # pragma: no cover - macOS fallback
                    os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, p)
        _fsync_dir(os.path.dirname(p))

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        try:
            xl = XLMeta.loads(self.read_xl(volume, path))
        except errors.FileNotFound:
            xl = XLMeta()
        xl.add_version(fi)
        self._write_xl(volume, path, xl)

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        xl = XLMeta.loads(self.read_xl(volume, path))
        if xl.find_version(fi.version_id) is None:
            raise errors.FileVersionNotFound(f"{volume}/{path}@{fi.version_id}")
        xl.add_version(fi)
        self._write_xl(volume, path, xl)

    def delete_version(self, volume: str, path: str, fi: FileInfo,
                       force_del_marker: bool = False) -> None:
        if fi.version_id == NULL_VERSION_ID:
            # API sentinel for the internal empty-id null version
            import dataclasses

            fi = dataclasses.replace(fi, version_id="")
        try:
            xl = XLMeta.loads(self.read_xl(volume, path))
        except errors.FileNotFound:
            if fi.deleted and force_del_marker:
                self.write_metadata(volume, path, fi)
                return
            raise
        if fi.deleted and not fi.version_id:
            # writing a delete marker on top; under suspended versioning the
            # marker has the null id and permanently replaces any existing
            # null version (AWS suspended-bucket semantics) — reclaim its data
            replaced = xl.add_version(fi)
            if replaced is not None and replaced.get("dd"):
                self._discard_dir(
                    os.path.join(self._file_path(volume, path),
                                 replaced["dd"]))
            self._write_xl(volume, path, xl)
            return
        v = xl.delete_version(fi.version_id)
        if v is None and fi.version_id:
            raise errors.FileVersionNotFound(f"{volume}/{path}@{fi.version_id}")
        if v is not None:
            data_dir = v.get("dd", "")
            if data_dir:
                dpath = os.path.join(self._file_path(volume, path), data_dir)
                self._discard_dir(dpath)
        if xl.versions:
            self._write_xl(volume, path, xl)
        elif self._journal is not None and volume != SYSTEM_VOL:
            # journaled unlink: durable once the group fsync lands,
            # tombstoned in the index, replayed idempotently on crash
            try:
                self._journal.unlink(volume, _clean(path))
            except metajournal.JournalDead:
                self._mark_index_stale()
                self.delete(volume, path, recursive=True)
        else:
            if volume != SYSTEM_VOL:
                self._mark_index_stale()
            self.delete(volume, path, recursive=True)

    def free_version_data(self, volume: str, path: str, version_id: str,
                          meta_updates: dict) -> None:
        """Drop a version's local data (parts dir + inline bytes) while
        keeping its xl.meta entry, merging `meta_updates` into the
        version's metadata — the tiering stub left behind after a
        transition (reference DeleteVersion w/ transition free-versions,
        cmd/xl-storage-free-version.go)."""
        if version_id == NULL_VERSION_ID:
            version_id = ""
        xl = XLMeta.loads(self.read_xl(volume, path))
        v = xl.find_version(version_id or "")
        if v is None or (version_id and v.get("v", "") != version_id):
            raise errors.FileVersionNotFound(f"{volume}/{path}@{version_id}")
        dd = v.get("dd", "")
        if dd:
            self._discard_dir(
                os.path.join(self._file_path(volume, path), dd))
        v["dd"] = ""
        v.pop("data", None)
        meta = v.setdefault("meta", {})
        meta.update(meta_updates)
        self._write_xl(volume, path, xl)

    def delete_versions(self, volume: str,
                         items: list) -> list:
        """Batched version deletes: items = [(path, FileInfo,
        force_del_marker)], one result slot per item (None = ok).
        Reference DeleteVersions (cmd/storage-interface.go,
        cmd/xl-storage.go DeleteVersions) — bulk deletes hit each drive
        once instead of once per object."""
        out = []
        for path, fi, force in items:
            try:
                self.delete_version(volume, path, fi,
                                    force_del_marker=force)
                out.append(None)
            except Exception as e:
                out.append(e)
        return out

    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None:
        """Move staged part files into place and commit xl.meta atomically."""
        dst_obj_dir = self._file_path(dst_volume, dst_path)
        os.makedirs(dst_obj_dir, exist_ok=True)
        if fi.data is None and fi.data_dir:
            src_dir = self._file_path(src_volume, src_path)
            if not os.path.isdir(src_dir):
                raise errors.FileNotFound(f"{src_volume}/{src_path}")
            if FSYNC_ENABLED:
                # shards written via append_file (remote streams) were not
                # synced per-chunk; make those durable before the rename
                # publishes the version.  Locally-streamed shards were
                # already fdatasync'd by _SyncedWriter.close — skip them.
                for name in os.listdir(src_dir):
                    fp = os.path.join(src_dir, name)
                    if fp in self._unsynced and os.path.isfile(fp):
                        with open(fp, "rb+") as f:
                            _fdatasync(f)
                        self._unsynced.discard(fp)
            dst_data_dir = os.path.join(dst_obj_dir, fi.data_dir)
            if os.path.isdir(dst_data_dir):
                self._discard_dir(dst_data_dir)
            if os.path.isdir(dst_data_dir):
                shutil.rmtree(dst_data_dir)  # trash move failed
            os.replace(src_dir, dst_data_dir)
            _fsync_dir(dst_obj_dir)
        try:
            xl = XLMeta.loads(self.read_xl(dst_volume, dst_path))
        except errors.FileNotFound:
            xl = XLMeta()
        replaced = xl.add_version(fi)
        self._write_xl(dst_volume, dst_path, xl)
        if replaced is not None and replaced.get("dd") \
                and replaced["dd"] != fi.data_dir:
            # overwrite of an unversioned / null version: reclaim the old
            # data dir (reference deletes old dataDir in RenameData,
            # cmd/xl-storage.go:1964)
            self._discard_dir(os.path.join(dst_obj_dir, replaced["dd"]))

    # -- listing ------------------------------------------------------------
    def list_dir(self, volume: str, path: str, count: int = -1) -> list[str]:
        p = self._file_path(volume, path) if path else self._vol_path(volume)
        try:
            entries = sorted(os.listdir(p))
        except FileNotFoundError:
            raise errors.FileNotFound(f"{volume}/{path}")
        out = []
        for e in entries:
            if os.path.isdir(os.path.join(p, e)):
                out.append(e + "/")
            else:
                out.append(e)
            if 0 < count <= len(out):
                break
        return out

    def walk_dir(self, volume: str, base: str = "",
                 recursive: bool = True) -> Iterator[str]:
        vol_root = self._vol_path(volume)
        if not os.path.isdir(vol_root):
            raise errors.VolumeNotFound(volume)
        start = os.path.join(vol_root, _clean(base)) if base else vol_root

        def walk(d: str, prefix: str) -> Iterator[str]:
            try:
                entries = sorted(os.listdir(d))
            except (FileNotFoundError, NotADirectoryError):
                return
            if XL_META_FILE in entries:
                yield prefix.rstrip("/")
                return
            for e in entries:
                sub = os.path.join(d, e)
                if os.path.isdir(sub):
                    if recursive:
                        yield from walk(sub, prefix + e + "/")
                    else:
                        yield prefix + e + "/"

        yield from walk(start, _clean(base) + "/" if base else "")

    # -- verification -------------------------------------------------------
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        from minio_tpu.erasure import bitrot

        if fi.erasure is None:
            raise errors.InvalidArgument("no erasure info")
        if fi.data is not None:
            return  # inline data verified via xl.meta integrity
        for part in fi.parts:
            shard_size = fi.erasure.shard_size
            shard_file_size = fi.erasure.shard_file_size(part.size)
            pp = os.path.join(self._file_path(volume, path), fi.data_dir,
                              f"part.{part.number}")
            try:
                f = open(pp, "rb")
            except FileNotFoundError:
                raise errors.FileNotFound(pp)
            with f:
                bitrot.bitrot_verify_stream(
                    f, os.fstat(f.fileno()).st_size, shard_file_size,
                    shard_size, algo=_stored_algo(fi),
                )

    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        if fi.data is not None:
            return
        from minio_tpu.erasure import bitrot

        for part in fi.parts:
            pp = os.path.join(self._file_path(volume, path), fi.data_dir,
                              f"part.{part.number}")
            try:
                st = os.stat(pp)
            except FileNotFoundError:
                raise errors.FileNotFound(pp)
            want = bitrot.bitrot_shard_file_size(
                fi.erasure.shard_file_size(part.size), fi.erasure.shard_size,
                _stored_algo(fi),
            )
            if st.st_size != want:
                raise errors.FileCorrupt(
                    f"{pp}: size {st.st_size} != expected {want}"
                )

    # -- misc ---------------------------------------------------------------
    def set_healing(self, healing: bool) -> None:
        p = self._sys_path(HEALING_FILE)
        if healing:
            with open(p, "w") as f:
                json.dump({"started": time.time()}, f)
        elif os.path.exists(p):
            os.remove(p)
