"""Storage-layer error types (reference: cmd/storage-errors.go)."""


class StorageError(Exception):
    pass


class DiskNotFound(StorageError):
    pass


class FaultyDisk(StorageError):
    """Drive returned an IO error (reference errFaultyDisk)."""
    pass


class FileNotFound(StorageError):
    pass


class FileVersionNotFound(StorageError):
    pass


class FileCorrupt(StorageError):
    pass


class VolumeNotFound(StorageError):
    pass


class VolumeExists(StorageError):
    pass


class DiskFull(StorageError):
    pass


class FileAccessDenied(StorageError):
    pass


class UnformattedDisk(StorageError):
    pass


class DeadlineExceeded(StorageError):
    """The caller's deadline budget ran out before the operation finished
    (reference context.DeadlineExceeded on the storage REST plane).  NOT
    a drive fault: the drive may be healthy, the request is just out of
    time — it must not feed the health circuit breaker."""


class ErasureReadQuorum(StorageError):
    """Not enough disks agree to serve a read (errErasureReadQuorum)."""


class ErasureWriteQuorum(StorageError):
    """Write did not reach quorum (errErasureWriteQuorum)."""


class ObjectNotFound(StorageError):
    pass


class VersionNotFound(StorageError):
    pass


class BucketNotFound(StorageError):
    pass


class BucketExists(StorageError):
    pass


class BucketNotEmpty(StorageError):
    pass


class InvalidArgument(StorageError):
    pass


class MethodNotAllowed(StorageError):
    pass


def reduce_errs(errs: list, ignored: tuple = ()) -> tuple[Exception | None, int]:
    """Return (most common error, count), treating None as success.

    Mirrors reduceErrs (cmd/erasure-metadata-utils.go:36): the modal error
    value decides the operation outcome.
    """
    counts: dict = {}
    for e in errs:
        if e is not None and any(isinstance(e, ig) for ig in ignored):
            continue
        key = None if e is None else (type(e), str(e))
        counts[key] = counts.get(key, 0) + 1
    if not counts:
        return None, 0
    # max count wins; ties prefer success (None)
    best_key = max(counts, key=lambda k: (counts[k], k is None))
    best = counts[best_key]
    if best_key is None:
        return None, best
    for e in errs:
        if e is not None and (type(e), str(e)) == best_key:
            return e, best
    return None, best


def reduce_quorum_errs(errs: list, ignored: tuple, quorum: int,
                       quorum_err: Exception) -> Exception | None:
    """Modal error if it meets quorum, else quorum_err
    (cmd/erasure-metadata-utils.go:62-90)."""
    err, count = reduce_errs(errs, ignored)
    if count >= quorum:
        return err
    return quorum_err
