"""Per-drive xl.meta commit journal + compacted sorted-segment index
(ISSUE 17 tentpole; protocol modeled first in
analysis/concurrency/models/metajournal.py).

The metadata-plane bottleneck at scale is per-commit durability: every
xl.meta write pays its own fdatasync + parent-dir fsync
(cmd/xl-storage.go:1667 equivalent in local.py _write_xl), so 32
concurrent PUTs pay 64 device flushes for a few KiB of metadata.  The
journal coalesces them: commits enqueue into a per-drive batch, a
committer thread appends the whole batch to an append-only journal
file, pays ONE group fdatasync, applies each xl.meta write BUFFERED
(tmp+rename, no per-file sync), and only then acks the waiters.  Crash
replay folds the surviving journal over the on-disk state — per-path
newest-sequence-wins, so re-apply is idempotent and a torn tail (only
ever the un-fsynced suffix, which was never acked) is safely dropped.
Rotation bounds the journal: once every record is applied it
fdatasyncs the CURRENT xl.meta of each distinct path the journal
mentions (a hot object overwritten 10k times pays one sync) and
truncates.

Layout under ``<drive>/.minio_tpu.sys/``::

    meta-journal/journal.bin      append-only record log
    meta-index/VALID              index trust marker (see below)
    meta-index/<bucket>/SEEDED    bucket baseline walked
    meta-index/<bucket>/seg-N.idx sorted segments, higher N = newer

Journal record: ``<len u32><crc32 u32><seq u64>`` header + payload
``<op u8><blen u16><bucket><plen u32><path><dlen u32><xl bytes>``
(op 1 = commit, 2 = unlink).  Replay stops at the first short or
CRC-failing record — appends are sequential and fsyncs are barriers,
so anything before the torn tail is intact.

The index is LSM-lite: journal applies feed an in-memory memtable
(``{bucket: {path: present}}``); rotation (or memtable pressure)
spills it as a sorted segment; lookups merge-read segments newest-
first with tombstone suppression; compaction folds a bucket's
segments into one when the count passes a threshold.  Segment files
are immutable: ``MTSI1`` magic, counts, then three sections loadable
as flat arrays — (count+1) u32 offsets, count u8 flags, a names blob
— so a continuation listing is a binary search over the blob, not a
parse of the file.

Index trust: segments only describe reality if every mutation since
they were written went through the journal.  A journal-off process
deletes ``VALID`` on its first object-metadata mutation; a journal-on
startup that finds ``VALID`` missing wipes the index and starts over
(buckets re-seed in the background).  Startup replay runs even
journal-off (LocalStorage always calls ``startup_replay``), so a
crashed journal-on process followed by a journal-off one never loses
acked commits or leaves a stale journal to clobber newer writes.
"""

from __future__ import annotations

import os
import shutil
import struct
import threading
import time
import zlib

import numpy as np

JOURNAL_DIR = "meta-journal"
JOURNAL_FILE = "journal.bin"
INDEX_DIR = "meta-index"
VALID_MARKER = "VALID"
SEEDED_MARKER = "SEEDED"
SEG_MAGIC = b"MTSI1\n"

OP_COMMIT = 1
OP_UNLINK = 2
#: bucket-deletion tombstone (path="", data=b""): folded newest-seq-wins
#: on replay — object records OLDER than their bucket's tombstone belong
#: to the deleted generation and must not resurrect the bucket dir
OP_BUCKET_DELETE = 3

_REC = struct.Struct("<IIQ")          # payload_len, crc32, seq
_SEG_HDR = struct.Struct("<6sII")     # magic, count, blob_len

#: master gate — default OFF; the journal-off path must stay
#: byte-identical to the pre-journal commit path
JOURNAL_ENABLED = os.environ.get(
    "MINIO_TPU_META_JOURNAL", "0").lower() in ("1", "on", "true")
#: max extra coalescing wait per flush (0 = opportunistic batching:
#: commits arriving while a group fsync is in flight form the next
#: batch — natural batching under load, no added latency when idle)
TICK_MS = float(os.environ.get("MINIO_TPU_META_JOURNAL_TICK_MS", "0"))
#: journal size that triggers rotation
ROTATE_BYTES = int(os.environ.get(
    "MINIO_TPU_META_JOURNAL_ROTATE_BYTES", str(8 << 20)))
#: byte budget per flush batch (larger batches split across flushes)
MAX_BATCH_BYTES = int(os.environ.get(
    "MINIO_TPU_META_JOURNAL_MAX_BATCH_BYTES", str(4 << 20)))
#: memtable entries that force a segment spill between rotations
MEMTABLE_SPILL = int(os.environ.get(
    "MINIO_TPU_META_INDEX_MEMTABLE", "16384"))
#: per-bucket segment count that triggers compaction
COMPACT_SEGMENTS = int(os.environ.get(
    "MINIO_TPU_META_INDEX_SEGMENTS", "8"))
#: committer seeds unseeded buckets in the background (tests disable
#: to control seeding explicitly)
AUTOSEED = os.environ.get(
    "MINIO_TPU_META_INDEX_AUTOSEED", "1").lower() in ("1", "on", "true")

XL_META_FILE = "xl.meta"


class JournalDead(Exception):
    """The committer thread is gone; callers fall back to the direct
    synced write path."""


class JournalKilled(BaseException):
    """Test-injected committer death (BaseException so nothing on the
    committer path accidentally swallows it)."""


#: test hook: set of named kill points; the committer dies when it
#: crosses an armed point (see tests/test_metajournal.py)
KILL_POINTS: set = set()


def _kill(point: str) -> None:
    if point in KILL_POINTS:
        raise JournalKilled(point)


def _fdatasync_fd(fd: int) -> None:
    if hasattr(os, "fdatasync"):
        os.fdatasync(fd)
    else:  # pragma: no cover - non-linux
        os.fsync(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _encode_payload(op: int, bucket: str, path: str, data: bytes) -> bytes:
    b = bucket.encode()
    p = path.encode()
    return struct.pack("<BH", op, len(b)) + b \
        + struct.pack("<I", len(p)) + p \
        + struct.pack("<I", len(data)) + data


def encode_record(seq: int, op: int, bucket: str, path: str,
                  data: bytes) -> bytes:
    payload = _encode_payload(op, bucket, path, data)
    return _REC.pack(len(payload), zlib.crc32(payload), seq) + payload


def decode_records(buf: bytes):
    """Yield (seq, op, bucket, path, data); stop at the torn tail."""
    pos, n = 0, len(buf)
    while pos + _REC.size <= n:
        plen, crc, seq = _REC.unpack_from(buf, pos)
        start = pos + _REC.size
        end = start + plen
        if end > n:
            return  # short record: the torn tail
        payload = buf[start:end]
        if zlib.crc32(payload) != crc:
            return  # corrupt tail record
        op, blen = struct.unpack_from("<BH", payload, 0)
        off = 3
        bucket = payload[off:off + blen].decode()
        off += blen
        (plen2,) = struct.unpack_from("<I", payload, off)
        off += 4
        path = payload[off:off + plen2].decode()
        off += plen2
        (dlen,) = struct.unpack_from("<I", payload, off)
        off += 4
        data = payload[off:off + dlen]
        yield seq, op, bucket, path, data
        pos = end


# ---------------------------------------------------------------------------
# sorted-segment index
# ---------------------------------------------------------------------------
class _Segment:
    """One immutable sorted segment, lazily loaded and cached: flat
    numpy offset/flag arrays over a names blob, so marker positioning
    is a binary search and iteration is zero-parse slicing."""

    def __init__(self, path: str, rank: int):
        self.path = path
        self.rank = rank
        self._loaded = None

    def _load(self):
        if self._loaded is None:
            with open(self.path, "rb") as f:
                raw = f.read()
            magic, count, blob_len = _SEG_HDR.unpack_from(raw, 0)
            if magic != SEG_MAGIC:
                raise ValueError(f"bad segment magic in {self.path}")
            off = _SEG_HDR.size
            offsets = np.frombuffer(raw, dtype="<u4", count=count + 1,
                                    offset=off)
            off += 4 * (count + 1)
            flags = np.frombuffer(raw, dtype="u1", count=count, offset=off)
            off += count
            blob = raw[off:off + blob_len]
            self._loaded = (offsets, flags, blob)
        return self._loaded

    def count(self) -> int:
        return int(self._load()[0].shape[0]) - 1

    def _name(self, i: int) -> bytes:
        offsets, _, blob = self._load()
        return blob[offsets[i]:offsets[i + 1]]

    def _lower_bound(self, key: bytes) -> int:
        lo, hi = 0, self.count()
        while lo < hi:
            mid = (lo + hi) // 2
            if self._name(mid) < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def iter_from(self, start_key: bytes):
        """Yield (name_bytes, rank, present) from the first name >=
        start_key."""
        offsets, flags, blob = self._load()
        n = self.count()
        i = self._lower_bound(start_key) if start_key else 0
        rank = self.rank
        while i < n:
            yield blob[offsets[i]:offsets[i + 1]], rank, bool(flags[i])
            i += 1


def _write_segment(path: str, items, fsync: bool) -> int:
    """items: sorted [(name_bytes, present)]; returns bytes written."""
    names = [n for n, _ in items]
    offsets = np.zeros(len(names) + 1, dtype="<u4")
    total = 0
    for i, n in enumerate(names):
        total += len(n)
        offsets[i + 1] = total
    flags = np.array([1 if p else 0 for _, p in items], dtype="u1")
    blob = b"".join(names)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_SEG_HDR.pack(SEG_MAGIC, len(names), len(blob)))
        f.write(offsets.tobytes())
        f.write(flags.tobytes())
        f.write(blob)
        f.flush()
        if fsync:
            _fdatasync_fd(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(os.path.dirname(path))
    return _SEG_HDR.size + offsets.nbytes + flags.nbytes + len(blob)


class MetaIndex:
    """Per-drive LSM-lite name index: memtable + sorted segments per
    bucket.  Writes come only from the journal committer; reads are
    safe from any thread."""

    def __init__(self, root: str, fsync: bool = True):
        self.root = root
        self.dir = os.path.join(root, ".minio_tpu.sys", INDEX_DIR)
        self.fsync = fsync
        self._lock = threading.Lock()
        self._mem: dict[str, dict[bytes, bool]] = {}
        self._segs: dict[str, list[_Segment]] = {}
        self._seeded: dict[str, bool] = {}
        self.compaction_bytes = 0
        self.spills = 0

    # -- validity -----------------------------------------------------------
    def _valid_path(self) -> str:
        return os.path.join(self.dir, VALID_MARKER)

    def is_valid(self) -> bool:
        return os.path.exists(self._valid_path())

    def invalidate(self) -> None:
        """Journal-off mutation: the index can no longer trust itself."""
        try:
            os.unlink(self._valid_path())
        except OSError:
            pass

    def activate(self) -> None:
        """Journal-on startup: wipe a stale index, then mark valid."""
        if not self.is_valid() and os.path.isdir(self.dir):
            for name in os.listdir(self.dir):
                p = os.path.join(self.dir, name)
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
                else:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
        os.makedirs(self.dir, exist_ok=True)
        with open(self._valid_path(), "w"):
            pass
        if self.fsync:
            _fsync_dir(self.dir)

    # -- per-bucket state ---------------------------------------------------
    def _bucket_dir(self, bucket: str) -> str:
        return os.path.join(self.dir, bucket)

    def _load_segs(self, bucket: str) -> list[_Segment]:
        segs = self._segs.get(bucket)
        if segs is None:
            segs = []
            d = self._bucket_dir(bucket)
            try:
                names = os.listdir(d)
            except OSError:
                names = []
            for name in sorted(names):
                if name.startswith("seg-") and name.endswith(".idx"):
                    rank = int(name[4:-4])
                    segs.append(_Segment(os.path.join(d, name), rank))
            segs.sort(key=lambda s: s.rank)
            self._segs[bucket] = segs
        return segs

    def bucket_seeded(self, bucket: str) -> bool:
        hit = self._seeded.get(bucket)
        if hit is None:
            hit = os.path.exists(
                os.path.join(self._bucket_dir(bucket), SEEDED_MARKER))
            self._seeded[bucket] = hit
        return hit

    def drop_bucket(self, bucket: str) -> None:
        """Bucket deleted: forget everything indexed under it."""
        with self._lock:
            self._mem.pop(bucket, None)
            self._segs.pop(bucket, None)
            self._seeded.pop(bucket, None)
        shutil.rmtree(self._bucket_dir(bucket), ignore_errors=True)

    # -- writes (committer thread only) -------------------------------------
    def apply(self, bucket: str, path: str, present: bool) -> None:
        self.apply_batch(((bucket, path, present),))

    def apply_batch(self, items) -> None:
        """Fold (bucket, path, present) triples into the memtable under
        ONE lock acquisition (the committer calls this once per batch)."""
        with self._lock:
            for bucket, path, present in items:
                self._mem.setdefault(bucket, {})[path.encode()] = present
        if sum(len(m) for m in self._mem.values()) >= MEMTABLE_SPILL:
            self.spill()

    def _next_rank(self, bucket: str) -> int:
        segs = self._load_segs(bucket)
        return (segs[-1].rank + 1) if segs else 1

    def spill(self) -> None:
        """Write each bucket's memtable as a new sorted segment.

        The segment write (file I/O + fdatasync) runs OUTSIDE the lock
        — the compact() pattern — so readers don't stall behind the
        device.  The memtable keeps its entries until the segment is
        published, then both flip in one locked section: a concurrent
        names() sees the entry in the memtable or in the segment,
        never in neither.  Spills come only from the committer thread,
        so the snapshot cannot lose concurrent writes."""
        with self._lock:
            plan = []
            for bucket, table in self._mem.items():
                if table:
                    plan.append((bucket, self._next_rank(bucket),
                                 sorted(table.items())))
        written = []
        for bucket, rank, items in plan:
            d = self._bucket_dir(bucket)
            os.makedirs(d, exist_ok=True)
            p = os.path.join(d, f"seg-{rank:08d}.idx")
            _write_segment(p, items, self.fsync)
            written.append((bucket, p, rank, items))
        with self._lock:
            for bucket, p, rank, items in written:
                self._load_segs(bucket).append(_Segment(p, rank))
                table = self._mem.get(bucket)
                if table is not None:
                    for name, _present in items:
                        table.pop(name, None)
                    if not table:
                        self._mem.pop(bucket, None)
                self.spills += 1
        self.maybe_compact()

    def seed(self, bucket: str, names) -> None:
        """Write the baseline segment (rank 0: every live segment
        outranks it) from a full walk of this drive's bucket dir."""
        d = self._bucket_dir(bucket)
        os.makedirs(d, exist_ok=True)
        items = sorted((n.encode(), True) for n in names)
        _write_segment(os.path.join(d, "seg-00000000.idx"), items,
                       self.fsync)
        with open(os.path.join(d, SEEDED_MARKER), "w"):
            pass
        if self.fsync:
            _fsync_dir(d)
        with self._lock:
            self._segs.pop(bucket, None)
            self._seeded[bucket] = True

    def maybe_compact(self) -> None:
        """Fold any bucket whose segment count passed the threshold
        into one segment (full merge: tombstones drop out)."""
        with self._lock:
            buckets = [b for b, segs in self._segs.items()
                       if len(segs) > COMPACT_SEGMENTS]
        for bucket in buckets:
            self.compact(bucket)

    def compact(self, bucket: str) -> None:
        with self._lock:
            segs = list(self._load_segs(bucket))
        if len(segs) <= 1:
            return
        merged = [(n, p) for n, p in self._merge(segs, {}, b"")
                  if p]  # full merge: tombstones die here
        d = self._bucket_dir(bucket)
        rank = segs[-1].rank + 1
        p = os.path.join(d, f"seg-{rank:08d}.idx")
        self.compaction_bytes += _write_segment(p, merged, self.fsync)
        with self._lock:
            keep = _Segment(p, rank)
            cur = self._load_segs(bucket)
            stale = [s for s in cur if s.rank <= segs[-1].rank]
            self._segs[bucket] = [s for s in cur
                                  if s.rank > segs[-1].rank] + [keep]
            self._segs[bucket].sort(key=lambda s: s.rank)
        for s in stale:
            try:
                os.unlink(s.path)
            except OSError:
                pass

    # -- reads --------------------------------------------------------------
    @staticmethod
    def _merge(segs, mem: dict, start_key: bytes):
        """Newest-wins merge of segment streams + a memtable snapshot,
        yielding sorted (name_bytes, present)."""
        import heapq

        streams = [s.iter_from(start_key) for s in segs]
        if mem:
            snap = sorted((k, v) for k, v in mem.items()
                          if not start_key or k >= start_key)
            streams.append((n, 1 << 30, p) for n, p in snap)
        last = None
        for name, _rank, present in heapq.merge(
                *streams, key=lambda t: (t[0], -t[1])):
            if name == last:
                continue  # an older rank's duplicate
            last = name
            yield name, present

    def names(self, bucket: str, prefix: str = "",
              marker: str = "") -> list[str] | None:
        """Sorted live names with `prefix`, from past `marker`; None if
        this drive's index can't serve the bucket (caller walks)."""
        if not self.is_valid() or not self.bucket_seeded(bucket):
            return None
        with self._lock:
            segs = list(self._load_segs(bucket))
            mem = dict(self._mem.get(bucket, {}))
        start = max(prefix, marker).encode()
        pfx = prefix.encode()
        out = []
        for name, present in self._merge(segs, mem, start):
            if pfx and not name.startswith(pfx):
                break  # sorted and name >= pfx: past the prefix range
            if present:
                out.append(name.decode())
        return out

    def segment_count(self) -> int:
        total = 0
        try:
            for b in os.listdir(self.dir):
                d = os.path.join(self.dir, b)
                if os.path.isdir(d):
                    total += sum(1 for n in os.listdir(d)
                                 if n.endswith(".idx"))
        except OSError:
            pass
        return total


# ---------------------------------------------------------------------------
# startup replay (runs journal-on AND journal-off)
# ---------------------------------------------------------------------------
def startup_replay(root: str, apply_commit, apply_unlink,
                   fsync: bool = True) -> int:
    """Fold a leftover journal over the drive's xl.meta state: apply
    the per-path NEWEST record (idempotent — every record carries the
    full xl.meta bytes), fdatasync each affected file, then truncate
    the journal.  Bucket-deletion tombstones fold by the same
    newest-seq-wins rule: the bucket dir is removed and older object
    records for it are dropped; records newer than the tombstone (the
    bucket was recreated) still apply.  Returns the number of paths
    replayed.

    Runs unconditionally at LocalStorage init so a crashed journal-on
    process followed by a journal-off one still recovers its acked
    commits — and leaves no stale journal behind to clobber newer
    journal-off writes."""
    jdir = os.path.join(root, ".minio_tpu.sys", JOURNAL_DIR)
    jpath = os.path.join(jdir, JOURNAL_FILE)
    try:
        with open(jpath, "rb") as f:
            buf = f.read()
    except OSError:
        return 0
    newest: dict[tuple, tuple] = {}
    tombs: dict[str, int] = {}
    for seq, op, bucket, path, data in decode_records(buf):
        if op == OP_BUCKET_DELETE and seq > tombs.get(bucket, -1):
            tombs[bucket] = seq
        prev = newest.get((bucket, path))
        if prev is None or seq > prev[0]:
            newest[(bucket, path)] = (seq, op, data)
    # bucket-deletion tombstones fold FIRST (newest-seq-wins, the same
    # rule as object records): the dir removal is idempotent, and any
    # object record older than its bucket's tombstone belongs to the
    # deleted generation — applying it would resurrect the bucket
    for bucket in tombs:
        shutil.rmtree(os.path.join(root, bucket), ignore_errors=True)
    if tombs and fsync:
        _fsync_dir(root)
    replayed = 0
    for (bucket, path), (seq, op, data) in newest.items():
        if op == OP_BUCKET_DELETE or seq < tombs.get(bucket, -1):
            continue
        replayed += 1
        if op == OP_COMMIT:
            apply_commit(bucket, path, bytes(data))
            if fsync:
                mp = os.path.join(root, bucket, path, XL_META_FILE)
                try:
                    fd = os.open(mp, os.O_RDONLY)
                except OSError:
                    continue
                try:
                    _fdatasync_fd(fd)
                finally:
                    os.close(fd)
        else:
            apply_unlink(bucket, path)
            if fsync:
                _fsync_dir(os.path.dirname(
                    os.path.join(root, bucket, path)))
    os.unlink(jpath)
    if fsync:
        _fsync_dir(jdir)
    return replayed


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------
class _Waiter:
    __slots__ = ("event", "err")

    def __init__(self):
        self.event = threading.Event()
        self.err = None


#: live journals, for metrics aggregation (server/metrics.py)
_JOURNALS: list = []
_JOURNALS_LOCK = threading.Lock()


def live_journals() -> list:
    with _JOURNALS_LOCK:
        return [j for j in _JOURNALS if not j.closed]


class MetaJournal:
    """One per drive.  `apply_commit(bucket, path, xl_bytes)` and
    `apply_unlink(bucket, path)` are the buffered (unsynced) apply
    callbacks LocalStorage provides; `list_names(bucket)` yields the
    drive's object names for background seeding."""

    def __init__(self, root: str, apply_commit, apply_unlink,
                 list_names=None, fsync: bool = True):
        self.root = root
        self.dir = os.path.join(root, ".minio_tpu.sys", JOURNAL_DIR)
        self.path = os.path.join(self.dir, JOURNAL_FILE)
        self.apply_commit = apply_commit
        self.apply_unlink = apply_unlink
        self.list_names = list_names
        self.fsync = fsync
        self.index = MetaIndex(root, fsync=fsync)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: list[tuple] = []  # (record_bytes, bucket, path,
        #                                 op, data, waiter)
        self._next_seq = 1
        self._dirty_paths: dict[tuple, int] = {}  # (bucket,path)->op
        self.closed = False
        self._dead = False

        # metrics
        self.commits = 0
        self.batches = 0
        self.flush_ns = 0
        self.last_batch = 0
        self.rotations = 0
        self.replayed = 0
        self.journal_bytes = 0

        os.makedirs(self.dir, exist_ok=True)
        # fold any leftover journal in, then start clean
        self.replayed = startup_replay(
            root, apply_commit, apply_unlink, fsync=fsync)
        self.index.activate()
        # raw append fd: one os.write per batch, no BufferedWriter
        # locking/flush on the hot path
        self._fd = os.open(self.path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._seed_scan_done = False
        # lint: allow(budget-propagation): per-drive committer is a long-lived daemon, budget-free by design — enqueuers block on the batch ack, so request deadlines stay with the caller
        self._thread = threading.Thread(
            target=self._run, name=f"meta-journal:{root}", daemon=True)
        self._thread.start()
        with _JOURNALS_LOCK:
            _JOURNALS.append(self)

    # -- client API ---------------------------------------------------------
    def commit(self, bucket: str, path: str, data: bytes) -> None:
        self._enqueue(OP_COMMIT, bucket, path, data)

    def unlink(self, bucket: str, path: str) -> None:
        self._enqueue(OP_UNLINK, bucket, path, b"")

    def bucket_delete(self, bucket: str) -> None:
        """Journal a bucket-deletion tombstone.  Blocks until the group
        fsync lands, so the tombstone is durable BEFORE the caller
        removes the bucket directory — a crash in between replays the
        tombstone instead of resurrecting journaled objects."""
        self._enqueue(OP_BUCKET_DELETE, bucket, "", b"")

    def _enqueue(self, op: int, bucket: str, path: str,
                 data: bytes) -> None:
        w = _Waiter()
        # payload + crc are seq-independent: build them OUTSIDE the lock
        # so 32-way producers don't serialize on the checksum
        payload = _encode_payload(op, bucket, path, data)
        crc = zlib.crc32(payload)
        with self._cond:
            if self._dead:
                raise JournalDead(self.root)
            seq = self._next_seq
            self._next_seq += 1
            rec = _REC.pack(len(payload), crc, seq) + payload
            self._queue.append((rec, bucket, path, op, data, w))
            self._cond.notify()
        w.event.wait()
        if w.err is not None:
            raise JournalDead(self.root) from w.err

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- committer ----------------------------------------------------------
    def _take_batch(self) -> list[tuple]:
        with self._cond:
            while not self._queue and not self.closed:
                self._cond.wait(timeout=0.5)
            if self.closed and not self._queue:
                return []
            if TICK_MS > 0:
                # optional coalescing window: let more commits join
                deadline = time.monotonic() + TICK_MS / 1e3
                while time.monotonic() < deadline:
                    self._cond.wait(timeout=TICK_MS / 1e3)
            q, size, k = self._queue, 0, 0
            while k < len(q) and size < MAX_BATCH_BYTES:
                size += len(q[k][0])
                k += 1
            batch = q[:k]
            del q[:k]  # one slice del, not O(n) pop(0) per item
            return batch

    def _run(self) -> None:
        batch: list[tuple] = []
        try:
            while True:
                batch = self._take_batch()
                if not batch:
                    if self.closed:
                        return
                    self._idle()
                    continue
                self._flush(batch)
                batch = []
                if self.journal_bytes >= ROTATE_BYTES:
                    self._rotate()
        except BaseException as e:  # committer must never die silently
            self._mark_dead(e, batch)

    def _mark_dead(self, err: BaseException, batch: list[tuple]) -> None:
        with self._cond:
            self._dead = True
            pending, self._queue = self._queue, []
            self._cond.notify_all()
        # wake every waiter with the error — including the in-flight
        # batch, whose commits died un-acked (a real SIGKILL would
        # leave their clients without a response the same way)
        for item in batch + pending:
            item[5].err = err
            item[5].event.set()

    def _flush(self, batch: list[tuple]) -> None:
        t0 = time.perf_counter_ns()
        _kill("pre_write")
        buf = b"".join(item[0] for item in batch)
        os.write(self._fd, buf)
        _kill("post_write")
        if self.fsync:
            _fdatasync_fd(self._fd)  # THE group fsync
        _kill("post_sync")
        with self._lock:
            self.journal_bytes += len(buf)
        # apply buffered, newest-seq-wins within the batch (same-path
        # records are already in seq order; the last write wins)
        for _rec, bucket, path, op, data, _w in batch:
            if op == OP_COMMIT:
                self.apply_commit(bucket, path, data)
                self._dirty_paths[(bucket, path)] = op
            elif op == OP_BUCKET_DELETE:
                # the caller removes the dir after the ack; here the
                # bucket's index dies and its pending rotate syncs are
                # moot (their files vanish with the dir)
                self.index.drop_bucket(bucket)
                for key in [k for k in self._dirty_paths
                            if k[0] == bucket]:
                    del self._dirty_paths[key]
            else:
                self.apply_unlink(bucket, path)
                self._dirty_paths[(bucket, path)] = op
            _kill("mid_apply")
        self.index.apply_batch(
            [(b, p, op == OP_COMMIT) for _r, b, p, op, _d, _w in batch
             if op != OP_BUCKET_DELETE])
        _kill("post_apply")
        # ack only now: the journal fsync above made the batch durable
        # and the applies made it visible (read-your-writes)
        for item in batch:
            item[5].event.set()
        # metrics threads read these lock-free (advisory); the WRITES
        # stay under the journal lock so the racecheck watches hold
        with self._lock:
            self.commits += len(batch)
            self.batches += 1
            self.last_batch = len(batch)
            self.flush_ns += time.perf_counter_ns() - t0

    def _rotate(self) -> None:
        """fdatasync the CURRENT xl.meta of each distinct dirty path
        (the dedup), spill the index memtable, then truncate."""
        _kill("pre_rotate")
        if self.fsync:
            for (bucket, path), op in self._dirty_paths.items():
                target = os.path.join(self.root, bucket, path)
                if op == OP_COMMIT:
                    try:
                        fd = os.open(os.path.join(target, XL_META_FILE),
                                     os.O_RDONLY)
                    except OSError:
                        continue  # deleted since; dir sync covers it
                    try:
                        _fdatasync_fd(fd)
                    finally:
                        os.close(fd)
                else:
                    _fsync_dir(os.path.dirname(target))
        self._dirty_paths.clear()
        self.index.spill()
        _kill("pre_truncate")
        # everything the journal holds is now durable in place:
        # truncate (atomic via ftruncate on the open append fd)
        os.ftruncate(self._fd, 0)  # O_APPEND fd: next write lands at 0
        if self.fsync:
            _fdatasync_fd(self._fd)
        with self._lock:
            self.journal_bytes = 0
            self.rotations += 1
        _kill("post_rotate")

    def _idle(self) -> None:
        """Background work between batches: compaction pressure and
        bucket seeding."""
        self.index.maybe_compact()
        if AUTOSEED and not self._seed_scan_done \
                and self.list_names is not None:
            self._seed_scan_done = True
            try:
                for bucket in sorted(os.listdir(self.root)):
                    if bucket.startswith("."):
                        continue
                    if not os.path.isdir(os.path.join(self.root, bucket)):
                        continue
                    if not self.index.bucket_seeded(bucket):
                        self.seed_bucket(bucket)
            except OSError:
                pass

    def seed_bucket(self, bucket: str) -> None:
        """Walk this drive's bucket dir and write the baseline
        segment.  Safe concurrent with live commits: the baseline
        ranks below every journal-fed segment, so newer state wins."""
        if self.list_names is None:
            return
        try:
            names = list(self.list_names(bucket))
        except Exception:
            return
        self.index.seed(bucket, names)

    # -- shutdown -----------------------------------------------------------
    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        try:
            os.close(self._fd)
        except OSError:
            pass

    def drain(self, timeout: float = 5.0) -> bool:
        """Test hook: wait for the queue to empty."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.queue_depth() == 0:
                return True
            time.sleep(0.002)
        return False


def metrics_snapshot() -> dict:
    """Aggregate journal/index counters across this process's drives
    (rendered by server/metrics.py as the minio_meta_* family)."""
    js = live_journals()
    if not js:
        return {}
    return {
        "journals": len(js),
        "queue_depth": sum(j.queue_depth() for j in js),
        "commits": sum(j.commits for j in js),
        "batches": sum(j.batches for j in js),
        "last_batch": max((j.last_batch for j in js), default=0),
        "flush_seconds": sum(j.flush_ns for j in js) / 1e9,
        "rotations": sum(j.rotations for j in js),
        "replayed": sum(j.replayed for j in js),
        "journal_bytes": sum(j.journal_bytes for j in js),
        "segments": sum(j.index.segment_count() for j in js),
        "compaction_bytes": sum(j.index.compaction_bytes for j in js),
        "spills": sum(j.index.spills for j in js),
    }
