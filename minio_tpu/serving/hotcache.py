"""Hot-object serving tier: in-RAM cache with request collapsing.

Heavy-traffic read workloads are dominated by a small hot set; today
every GET — even a repeat GET of the same immutable object — pays the
full erasure path (xl.meta quorum read, k shard opens, bitrot verify,
RS decode).  This tier sits ABOVE the erasure layer (the disk-backed
analogue is gateway/cache.py; reference shape: cmd/disk-cache.go) and
holds decoded object bytes plus the ObjectInfo needed to answer
headers, so a hit performs ZERO storage calls — conditional GETs
(If-None-Match / If-Modified-Since) 304 without touching xl.meta and
Range requests slice the resident buffer.

Three mechanisms carry the design:

* Segmented LRU + TinyLFU-style admission.  Entries are admitted into a
  probation segment and promoted to a protected segment (~80% of the
  byte budget) on re-reference, so a scan of one-hit wonders cannot
  flush the established hot set.  Admission itself is gated on a
  per-key access-frequency counter with periodic halving (TinyLFU
  aging): an object's bytes are only cached from its
  `MINIO_TPU_HOTCACHE_MIN_HITS`-th access on (default 2), and objects
  over `MINIO_TPU_HOTCACHE_MAX_OBJ_BYTES` are never admitted so one
  huge object cannot evict the whole tier.

* Request collapsing (singleflight).  Concurrent GETs for the same
  (bucket, object, version) share ONE erasure read: the first caller
  becomes the fill leader, late arrivals stream from the filling buffer
  AS IT GROWS (no wait-for-whole-object), and losers of the race never
  touch drives — the memcache-style thundering-herd defense.  Collapse
  applies even to keys the admission filter later declines: the
  back-end read is shared either way.  The price is leader latency —
  the leader's own first byte waits for the full back-end read (a
  follower's does not) — which is why max_obj_bytes defaults small
  (<= 64 MiB) and total in-flight fill RAM is capped at the tier
  budget; over the cap a request streams classically, unbuffered.

* Strict invalidation through one choke point.  Every mutation of an
  object — overwrite PUT, CompleteMultipartUpload, CopyObject onto a
  cached destination, DELETE / version delete, heal and replication
  rewrites — fires the erasure layer's `ns_updated` hook
  (erasure/objects.py), which calls `invalidate()` here.  Invalidation
  drops the entries AND bumps a per-object generation counter; a fill
  commits only if the generation it started under is still current, and
  a hit re-validates its entry's generation, so a racing writer can
  never leave stale bytes serveable.

The tier is off by default: set `MINIO_TPU_HOTCACHE_BYTES` to enable.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Iterator

from minio_tpu.utils import tracing

_mono = time.monotonic

#: fraction of the byte budget reserved for the protected SLRU segment
PROTECTED_FRAC = 0.8

#: frequency-sketch aging: halve all counters after this many accesses
#: (or when the sketch grows past _FREQ_MAX_KEYS) — TinyLFU's reset,
#: keeping the sketch a bounded recency-weighted estimate.  The key cap
#: also bounds the rebuild's ``_mu`` hold time: every lock hold in this
#: module must stay small because lookup() runs on the event loop
_FREQ_AGE_OPS = 1 << 16
_FREQ_MAX_KEYS = 1 << 13

#: admission declines rather than evict more than this many entries in
#: one sweep: a single object displacing thousands of tiny entries is a
#: poor cache trade AND would hold ``_mu`` through an O(n) sweep while
#: the event loop's lookup() waits behind it
_EVICT_SWEEP_MAX = 256

#: streaming chunk size for followers reading a growing fill buffer
_STREAM_CHUNK = 1 << 18


def from_env() -> "HotObjectCache | None":
    """Build the tier from env knobs; None when disabled (default)."""
    try:
        max_bytes = int(os.environ.get("MINIO_TPU_HOTCACHE_BYTES", "0"))
    except ValueError:
        max_bytes = 0
    if max_bytes <= 0:
        return None
    def _int_env(name: str) -> int | None:
        # a malformed sibling knob degrades to its default, same as a
        # malformed MINIO_TPU_HOTCACHE_BYTES disables the tier —
        # an operator typo must not fail server boot
        try:
            v = os.environ.get(name, "")
            return int(v) if v else None
        except ValueError:
            return None

    min_hits = _int_env("MINIO_TPU_HOTCACHE_MIN_HITS")
    try:
        ttl_s = float(os.environ.get("MINIO_TPU_HOTCACHE_TTL_S", "") or 0)
    except ValueError:
        ttl_s = 0.0
    return HotObjectCache(
        max_bytes,
        max_obj_bytes=_int_env("MINIO_TPU_HOTCACHE_MAX_OBJ_BYTES"),
        min_hits=2 if min_hits is None else min_hits,
        ttl_s=ttl_s,
    )


class _Entry:
    __slots__ = ("key", "oi", "data", "gen", "ts")

    def __init__(self, key, oi, data: bytes, gen: int, ts: float = 0.0):
        self.key = key
        self.oi = oi
        self.data = data
        self.gen = gen
        self.ts = ts  # admit time (monotonic) for the TTL backstop


class _Fill:
    """Per-key singleflight latch: the leader appends decoded chunks,
    followers stream from the buffer as it grows.  Terminal states:

    * ``done``   — full object buffered; `oi` set
    * ``miss``   — object exists but is not cacheable (SSE / compressed
                   / tiered / too big); `oi` set, no data — followers
                   fall back to their own read
    * ``failed`` — the back-end read raised; `error` set — followers
                   re-raise the leader's error (collapsed 404s included)
    """

    __slots__ = ("gen", "cv", "buf", "oi", "state", "error", "reserved")

    def __init__(self, gen: int):
        self.gen = gen
        self.cv = threading.Condition()
        self.buf = bytearray()
        self.oi = None
        self.state = "filling"
        self.error: BaseException | None = None
        self.reserved = 0  # bytes charged against the fill-RAM cap

    def append(self, chunk) -> None:
        with self.cv:
            self.buf += chunk
            self.cv.notify_all()

    def set_oi(self, oi) -> None:
        with self.cv:
            self.oi = oi
            self.cv.notify_all()

    def settle(self, state: str, oi=None,
               error: BaseException | None = None) -> None:
        with self.cv:
            if oi is not None:
                self.oi = oi
            self.error = error
            self.state = state
            self.cv.notify_all()

    def wait_header(self):
        """Block until the leader has resolved the object's identity
        (oi known) or the fill reached a terminal state."""
        with self.cv:
            while self.oi is None and self.state == "filling":
                self.cv.wait(1.0)
            return self.state, self.oi, self.error

    def stream(self) -> Iterator[bytes]:
        """Yield the buffer progressively; completes when the leader
        settles.  Raises the leader's error on a failed fill."""
        pos = 0
        while True:
            with self.cv:
                while len(self.buf) <= pos and self.state == "filling":
                    self.cv.wait(1.0)
                if self.error is not None:
                    raise self.error
                chunk = bytes(self.buf[pos:pos + _STREAM_CHUNK])
                finished = self.state != "filling" \
                    and pos + len(chunk) >= len(self.buf)
            if chunk:
                pos += len(chunk)
                yield chunk
            if finished:
                return


class HotObjectCache:
    """Size-bounded in-RAM hot-object tier keyed by
    (bucket, object, version)."""

    def __init__(self, max_bytes: int, max_obj_bytes: int | None = None,
                 min_hits: int = 2, ttl_s: float = 0.0):
        #: TTL backstop (seconds; 0 = entries live until invalidated).
        #: On a DISTRIBUTED deployment invalidation rides a best-effort
        #: peer broadcast (distributed/peers.py hotcache_invalidate) —
        #: a peer that misses it (down, partitioned) must still
        #: converge, so the cluster wiring sets a nonzero TTL bounding
        #: the worst-case staleness window (ISSUE 8 satellite).
        self.ttl_s = float(ttl_s)
        self.max_bytes = int(max_bytes)
        if max_obj_bytes is None:
            # one object may take at most 1/8 of the tier (floor 1 MiB),
            # AND no more than 64 MiB by default: the fill leader
            # buffers the whole object before its client's first byte
            # (the price of cold-herd collapse), so the default keeps
            # that worst-case TTFB small even under a many-GiB tier —
            # operators caching bigger objects raise the env knob
            max_obj_bytes = max(min(self.max_bytes // 8, 64 << 20),
                                1 << 20)
        self.max_obj_bytes = min(int(max_obj_bytes), self.max_bytes)
        self.min_hits = max(1, int(min_hits))
        self._mu = threading.Lock()
        self._prob: "OrderedDict" = OrderedDict()  # probation segment
        self._prot: "OrderedDict" = OrderedDict()  # protected segment
        self._bytes = 0
        self._prot_bytes = 0
        self._by_obj: dict = {}   # (bucket, obj) -> set of entry keys
        self._fills: dict = {}    # key3 -> _Fill
        self._fill_bytes = 0      # reserved RAM of in-flight fills
        self._gen: dict = {}      # (bucket, obj) -> generation value
        self._gen_src = itertools.count(1)
        self._freq: dict = {}     # key3 -> access count (aged)
        self._freq_ops = 0
        # counters (surfaced as minio_hotcache_* in server/metrics.py)
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.collapsed = 0
        self.evictions = 0
        self.invalidations = 0

    # ------------------------------------------------------------ internals
    def _note_access_locked(self, k) -> None:
        self._freq[k] = self._freq.get(k, 0) + 1
        self._freq_ops += 1
        if self._freq_ops >= _FREQ_AGE_OPS \
                or len(self._freq) > _FREQ_MAX_KEYS:
            self._freq = {kk: c // 2 for kk, c in self._freq.items()
                          if c // 2 > 0}
            self._freq_ops = 0

    def _gen_of_locked(self, bo) -> int:
        g = self._gen.get(bo)
        if g is None:
            g = next(self._gen_src)
            self._gen[bo] = g
        return g

    def _maybe_drop_gen_locked(self, bo) -> None:
        """Generation cells live only while an entry or fill references
        the object, so the dict cannot grow with one-shot keys."""
        if self._by_obj.get(bo):
            return
        if any(k[0] == bo[0] and k[1] == bo[1] for k in self._fills):
            return
        self._gen.pop(bo, None)
        self._by_obj.pop(bo, None)

    def _drop_entry_locked(self, k, *, count_eviction: bool) -> None:
        ent = self._prob.pop(k, None)
        if ent is None:
            ent = self._prot.pop(k, None)
            if ent is not None:
                self._prot_bytes -= len(ent.data)
        if ent is None:
            return
        self._bytes -= len(ent.data)
        if count_eviction:
            self.evictions += 1
        bo = (k[0], k[1])
        keys = self._by_obj.get(bo)
        if keys is not None:
            keys.discard(k)
            if not keys:
                self._by_obj.pop(bo, None)
        self._maybe_drop_gen_locked(bo)

    def _evict_locked(self) -> None:
        while self._bytes > self.max_bytes and (self._prob or self._prot):
            src = self._prob if self._prob else self._prot
            k, _ = next(iter(src.items()))
            self._drop_entry_locked(k, count_eviction=True)

    def _admit_locked(self, k, oi, data: bytes, gen: int) -> None:
        self._drop_entry_locked(k, count_eviction=False)
        need = self._bytes + len(data) - self.max_bytes
        if need > 0:
            # count prospective victims in eviction order (probation
            # LRU-first, then protected) WITHOUT popping: if making
            # room exceeds the bounded sweep, decline the admission
            freed = n = 0
            for src in (self._prob, self._prot):
                for ent in src.values():
                    if freed >= need or n > _EVICT_SWEEP_MAX:
                        break
                    freed += len(ent.data)
                    n += 1
                if freed >= need or n > _EVICT_SWEEP_MAX:
                    break
            if n > _EVICT_SWEEP_MAX:
                return
        # a frozen metadata copy: callers treat cached ObjectInfo as
        # read-only, but the erasure layer hands out live dicts
        oi = dataclasses.replace(oi, metadata=dict(oi.metadata),
                                 parts=list(oi.parts))
        self._prob[k] = _Entry(k, oi, data, gen, ts=_mono())
        self._bytes += len(data)
        self._by_obj.setdefault((k[0], k[1]), set()).add(k)
        self._evict_locked()

    def _touch_locked(self, k, ent: _Entry) -> None:
        """SLRU promotion: probation hit moves to protected; protected
        overflow demotes its LRU back to probation (not out)."""
        if k in self._prob:
            self._prob.pop(k)
            self._prot[k] = ent
            self._prot_bytes += len(ent.data)
            cap = self.max_bytes * PROTECTED_FRAC
            while self._prot_bytes > cap and len(self._prot) > 1:
                dk, dent = next(iter(self._prot.items()))
                self._prot.pop(dk)
                self._prot_bytes -= len(dent.data)
                self._prob[dk] = dent
        elif k in self._prot:
            self._prot.move_to_end(k)

    def _entry_locked(self, k) -> _Entry | None:
        ent = self._prob.get(k)
        if ent is None:
            ent = self._prot.get(k)
        if ent is None:
            return None
        if self._gen.get((k[0], k[1])) != ent.gen:
            # a writer invalidated between admit and now: never serve
            self._drop_entry_locked(k, count_eviction=False)
            return None
        if self.ttl_s > 0 and _mono() - ent.ts > self.ttl_s:
            # TTL backstop expired: re-read through the erasure layer
            # (a missed peer broadcast can leave this entry stale)
            self._drop_entry_locked(k, count_eviction=False)
            return None
        return ent

    # ------------------------------------------------------------- queries
    def probe(self, bucket: str, obj: str, version_id: str = "") -> bool:
        """Advisory hit test for the admission fast lane: no counters,
        no LRU movement, and deliberately LOCK-FREE — it runs on the
        event loop, which must never wait behind an executor thread
        holding ``_mu`` through an eviction sweep or frequency aging.
        Single dict reads are safe under the GIL; a stale answer only
        mis-picks the admission lane, and lookup() re-validates under
        the lock before any bytes are served."""
        k = (bucket, obj, version_id)
        ent = self._prob.get(k) or self._prot.get(k)
        return ent is not None \
            and self._gen.get((bucket, obj)) == ent.gen \
            and not (self.ttl_s > 0 and _mono() - ent.ts > self.ttl_s)

    def lookup(self, bucket: str, obj: str, version_id: str = "", *,
               count_miss: bool = True) -> _Entry | None:
        """Hit path: entry with a generation-valid ObjectInfo + bytes,
        or None.

        ``count_miss=True`` (HEAD, Range — requests whose miss falls
        through to the classic path and never reaches serve()) counts
        the miss and feeds the admission sketch here, so Range/HEAD-hot
        objects can clear the min-hits gate and the hit-ratio gauge
        stays honest.  Whole-object GET misses pass ``count_miss=False``
        because serve() counts that same request — counting twice would
        defeat the 2nd-access admission gate."""
        k = (bucket, obj, version_id)
        with self._mu:
            ent = self._entry_locked(k)
            if ent is None:
                if count_miss:
                    self._note_access_locked(k)
                    self.misses += 1
            else:
                self._note_access_locked(k)
                self._touch_locked(k, ent)
                self.hits += 1
        # trace mark outside the lock; the RAM-hit path is THE hot path
        # so the verdict rides the root span's tags (annotate — no span
        # record) instead of an event span
        if ent is not None:
            tracing.annotate(hotcache="hit")
        elif count_miss:
            tracing.annotate(hotcache="miss")
        return ent

    def cacheable(self, oi) -> bool:
        """Only plain, fully-resident objects are admitted: encrypted
        bytes must not sit decrypted in RAM, compressed objects would
        double-store, tiered stubs have no local bytes, and anything
        over max_obj_bytes would flush the tier."""
        from minio_tpu.crypto import sse as sse_mod
        from minio_tpu.erasure.objects import (TRANSITION_COMPLETE,
                                               TRANSITION_STATUS_KEY)
        from minio_tpu.utils import compress as compress_mod

        if oi.delete_marker or not oi.etag:
            return False
        if oi.size > self.max_obj_bytes:
            return False
        md = oi.metadata
        if md.get(sse_mod.META_ALGO):
            return False
        if md.get(compress_mod.META_COMPRESSION):
            return False
        if md.get(TRANSITION_STATUS_KEY) == TRANSITION_COMPLETE:
            return False
        return True

    # --------------------------------------------------------------- serve
    def serve(self, bucket: str, obj: str, version_id: str,
              info_fn: Callable, data_fn: Callable):
        """Miss path with request collapsing.  Returns (kind, oi,
        payload):

        * ("hit", oi, bytes)        — admitted while we queued
        * ("filled", oi, bytes)     — this caller led the one erasure
                                      read; bytes are the whole object
        * ("collapsed", oi, iter)   — joined another caller's fill;
                                      payload streams from the growing
                                      buffer (no drive touched)
        * ("miss", oi, None)        — object not cacheable; caller runs
                                      the classic path reusing `oi`

        info_fn() -> ObjectInfo and data_fn() -> (ObjectInfo, stream)
        are only invoked by the fill leader.  Back-end errors (including
        NotFound) propagate to every collapsed caller.
        """
        k = (bucket, obj, version_id)
        bo = (bucket, obj)
        with self._mu:
            self._note_access_locked(k)
            ent = self._entry_locked(k)
            if ent is not None:
                self._touch_locked(k, ent)
                self.hits += 1
            else:
                self.misses += 1
                fill = self._fills.get(k)
                if fill is not None:
                    self.collapsed += 1
                    follower = fill
                else:
                    follower = None
                    fill = _Fill(self._gen_of_locked(bo))
                    self._fills[k] = fill
        if ent is not None:
            tracing.event("hotcache", outcome="hit")
            return ("hit", ent.oi, ent.data)
        if follower is not None:
            # collapsed follower: this request streams from another
            # request's in-flight fill — zero drive reads of its own
            tracing.event("hotcache", outcome="collapsed-follower")
            return self._follow(follower)
        return self._lead(k, bo, fill, info_fn, data_fn)

    def _follow(self, fill: _Fill):
        state, oi, err = fill.wait_header()
        if err is not None:
            raise err
        if state == "miss":
            # leader resolved the object as uncacheable: hand back its
            # oi, the caller reads drives itself (ineligible objects
            # are the one case collapse does not cover)
            return ("miss", oi, None)
        # "filling" with oi set (leader committed to buffering) or
        # "done": stream from the buffer; a later leader failure
        # surfaces through the stream
        return ("collapsed", oi, fill.stream())

    def _lead(self, k, bo, fill: _Fill, info_fn, data_fn):
        tracing.event("hotcache", outcome="fill-leader")
        try:
            oi = info_fn()
        except BaseException as e:
            self._finish(k, bo, fill, state="failed", error=e)
            raise
        if not self.cacheable(oi):
            self._finish(k, bo, fill, state="miss", oi=oi)
            tracing.event("hotcache", outcome="miss", cacheable=False)
            return ("miss", oi, None)
        with self._mu:
            # bound TOTAL in-flight fill RAM by the tier budget: the
            # entry store is capped at max_bytes, and without this a
            # burst of concurrent cold GETs of distinct large-ish
            # objects could hold an unbounded sum of fill buffers
            # outside that accounting
            fits = self._fill_bytes + oi.size <= self.max_bytes
            if fits:
                fill.reserved = oi.size
                self._fill_bytes += oi.size
        if not fits:
            # over the cap this request takes the classic streaming
            # path (no collapse, no buffering) — the pre-tier behavior
            self._finish(k, bo, fill, state="miss", oi=oi)
            return ("miss", oi, None)
        fill.set_oi(oi)
        stream = None
        try:
            _, stream = data_fn()
            for chunk in stream:
                fill.append(chunk)
                if len(fill.buf) > oi.size:
                    # stream longer than the ObjectInfo we told the
                    # followers about (racing overwrite between
                    # info_fn and data_fn): fail fast at oi.size, not
                    # after buffering up to the per-object cap
                    raise IOError(
                        "hotcache fill overran the object size")
        except BaseException as e:
            self._finish(k, bo, fill, state="failed", error=e)
            raise
        finally:
            close = getattr(stream, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        data = bytes(fill.buf)
        if len(data) != oi.size:
            e = IOError(f"hotcache fill short read: "
                        f"{len(data)} != {oi.size}")
            self._finish(k, bo, fill, state="failed", error=e)
            raise e
        self._finish(k, bo, fill, state="done", oi=oi, data=data)
        return ("filled", oi, data)

    def _finish(self, k, bo, fill: _Fill, *, state: str, oi=None,
                data: bytes | None = None,
                error: BaseException | None = None) -> None:
        with self._mu:
            # identity check: invalidate() may have detached this fill
            # and a successor fill may occupy the key by now
            if self._fills.get(k) is fill:
                self._fills.pop(k)
            self._fill_bytes -= fill.reserved
            fill.reserved = 0
            if data is not None:
                self.fills += 1
                # commit ONLY if no writer invalidated since the fill
                # started (generation unchanged) and the admission
                # filter has seen enough demand for this key
                if self._gen.get(bo) == fill.gen \
                        and self._freq.get(k, 0) >= self.min_hits:
                    self._admit_locked(k, oi, data, fill.gen)
            self._maybe_drop_gen_locked(bo)
        fill.settle(state, oi=oi, error=error)

    # ---------------------------------------------------------- choke point
    def invalidate(self, bucket: str, obj: str) -> None:
        """The single invalidation choke point, fired by the erasure
        layer's ns_updated hook on EVERY object mutation (overwrite PUT,
        multipart complete, copy, delete, version delete, heal /
        replication rewrites).  Drops all cached versions of the object
        and bumps its generation so in-flight fills cannot commit."""
        bo = (bucket, obj)
        with self._mu:
            keys = self._by_obj.get(bo)
            stale = [fk for fk in self._fills
                     if fk[0] == bucket and fk[1] == obj]
            if not keys and not stale and bo not in self._gen:
                return
            for k in list(keys or ()):
                self._drop_entry_locked(k, count_eviction=False)
            self._gen.pop(bo, None)
            for fk in stale:
                # DETACH in-flight fills: their existing followers keep
                # streaming the pre-write view (those GETs began before
                # the write), but a GET arriving from here on must not
                # join a fill that started before this mutation — it
                # leads a fresh erasure read instead (read-after-write).
                # The detached fill can never commit: its generation
                # predates this bump (the counter never reuses values).
                self._fills.pop(fk)
            self.invalidations += 1

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        # lock-free advisory snapshot (same reasoning as probe(): the
        # metrics scrape runs on the event loop, and plain int/len
        # reads are consistent-enough under the GIL — a scrape racing
        # an admit may be one entry off, never torn)
        looked = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "fills": self.fills,
            "collapsed": self.collapsed,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
            "bytes": self._bytes,
            "fillBytes": self._fill_bytes,
            "entries": len(self._prob) + len(self._prot),
            "protectedBytes": self._prot_bytes,
            "maxBytes": self.max_bytes,
            "maxObjBytes": self.max_obj_bytes,
            "ttlSeconds": self.ttl_s,
            "hitRatio": round(self.hits / looked, 6) if looked
            else 0.0,
        }
