"""Serving-tier layers that sit ABOVE the erasure object layer.

`hotcache` is the in-RAM hot-object tier (ISSUE 7): million-user read
fan-in is dominated by a small hot set, and a repeat GET of an
immutable object should not re-pay the xl.meta quorum read, k shard
opens, bitrot verify and RS decode every time.
"""

from .hotcache import HotObjectCache, from_env

__all__ = ["HotObjectCache", "from_env"]
