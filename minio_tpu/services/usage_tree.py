"""Hierarchical data-usage tree: per-folder stats, persisted per set,
merged across sets/pools.

Reference: cmd/data-usage-cache.go (dataUsageCache — a tree of
dataUsageEntry keyed by folder hash, persisted per drive, merged for
admin queries) + cmd/data-scanner.go:368 (subtree-bounded rescans).

A node holds the stats of objects directly in its folder ("own") plus
children folders; subtree queries aggregate on demand.  Depth and fanout
are capped like the reference's: entries below the cap fold into their
parent's own-stats so one pathological bucket cannot balloon the tree.
"""

from __future__ import annotations

from dataclasses import dataclass, field

MAX_DEPTH = 8        # folders deeper than this fold into the ancestor
MAX_CHILDREN = 1024  # per-node fanout cap below the top level
MAX_TOP = 1 << 16    # top-level cap; beyond it entries fold into root.own
                     # and subtree-bounded rescans degrade to full walks


def _histogram_bucket(size: int) -> str:
    from .scanner import _histogram_bucket as hb

    return hb(size)


@dataclass
class _Stats:
    objects: int = 0
    versions: int = 0
    delete_markers: int = 0
    size: int = 0
    histogram: dict = field(default_factory=dict)

    def add(self, size: int, versions: int, delete_markers: int) -> None:
        if delete_markers and not versions:
            self.delete_markers += delete_markers
            return
        self.objects += 1
        self.versions += versions
        self.delete_markers += delete_markers
        self.size += size
        b = _histogram_bucket(size)
        self.histogram[b] = self.histogram.get(b, 0) + 1

    def merge(self, other: "_Stats") -> None:
        self.objects += other.objects
        self.versions += other.versions
        self.delete_markers += other.delete_markers
        self.size += other.size
        for k, v in other.histogram.items():
            self.histogram[k] = self.histogram.get(k, 0) + v

    def to_dict(self) -> dict:
        return {"objects": self.objects, "versions": self.versions,
                "deleteMarkers": self.delete_markers, "size": self.size,
                "histogram": self.histogram}

    @classmethod
    def from_dict(cls, d: dict) -> "_Stats":
        s = cls(objects=d.get("objects", 0), versions=d.get("versions", 0),
                delete_markers=d.get("deleteMarkers", 0),
                size=d.get("size", 0))
        s.histogram = dict(d.get("histogram", {}))
        return s


class _Node:
    __slots__ = ("own", "children")

    def __init__(self):
        self.own = _Stats()
        self.children: dict[str, _Node] = {}


class UsageTree:
    """One bucket's folder tree."""

    def __init__(self):
        self.root = _Node()

    # -- building -----------------------------------------------------------
    def add(self, obj: str, size: int, versions: int = 1,
            delete_markers: int = 0) -> None:
        """Objects count in their parent folder's node; root-level
        objects become leaf children keyed by name, so every top-level
        segment is independently replaceable by a bounded rescan."""
        parts = obj.split("/")
        node = self.root
        if len(parts) == 1:
            child = node.children.get(parts[0])
            if child is None:
                if len(node.children) >= MAX_TOP:
                    node.own.add(size, versions, delete_markers)
                    return
                child = node.children[parts[0]] = _Node()
            child.own.add(size, versions, delete_markers)
            return
        for depth, seg in enumerate(parts[:-1]):
            if depth >= MAX_DEPTH:
                break  # too deep: count the object at this ancestor
            child = node.children.get(seg)
            if child is None:
                if depth > 0 and len(node.children) >= MAX_CHILDREN:
                    break  # fanout cap: fold into the parent's own stats
                child = node.children[seg] = _Node()
            node = child
        node.own.add(size, versions, delete_markers)

    # -- selective rescan (subtree-bounded cycles) --------------------------
    def top_segments(self) -> list[str]:
        return sorted(self.root.children)

    def drop_top(self, seg: str) -> None:
        self.root.children.pop(seg, None)

    def replace_top(self, seg: str, subtree: "UsageTree") -> None:
        """Install `subtree`'s content under top-level `seg`.  The
        subtree must have been built from paths that all start with
        `seg + '/'` (or equal `seg` for a root-level object)."""
        child = subtree.root.children.get(seg)
        if child is None:
            self.root.children.pop(seg, None)
            return
        self.root.children[seg] = child

    def clone(self) -> "UsageTree":
        t = UsageTree()
        t.root = _clone_node(self.root)
        return t

    # -- queries ------------------------------------------------------------
    def _find(self, prefix: str) -> _Node | None:
        node = self.root
        for seg in [s for s in prefix.split("/") if s]:
            node = node.children.get(seg)
            if node is None:
                return None
        return node

    def subtree(self, prefix: str = "") -> dict:
        """Aggregated usage at/under `prefix` ('' = whole bucket)."""
        node = self._find(prefix)
        agg = _Stats()
        if node is not None:
            _aggregate(node, agg)
        return agg.to_dict()

    def children_of(self, prefix: str = "") -> dict[str, dict]:
        """Immediate sub-folders of `prefix` with their aggregates (the
        admin 'du' view, reference madmin DataUsageInfo by prefix)."""
        node = self._find(prefix)
        if node is None:
            return {}
        out = {}
        for seg, child in sorted(node.children.items()):
            agg = _Stats()
            _aggregate(child, agg)
            out[seg] = agg.to_dict()
        return out

    def totals(self) -> dict:
        return self.subtree("")

    # -- merge / persistence -------------------------------------------------
    def merge(self, other: "UsageTree") -> None:
        _merge_node(self.root, other.root)

    def to_dict(self) -> dict:
        return _node_to_dict(self.root)

    @classmethod
    def from_dict(cls, d: dict) -> "UsageTree":
        t = cls()
        t.root = _node_from_dict(d)
        return t


def _aggregate(node: _Node, agg: _Stats) -> None:
    agg.merge(node.own)
    for child in node.children.values():
        _aggregate(child, agg)


def _merge_node(dst: _Node, src: _Node) -> None:
    dst.own.merge(src.own)
    for seg, child in src.children.items():
        mine = dst.children.get(seg)
        if mine is None:
            dst.children[seg] = _clone_node(child)
        else:
            _merge_node(mine, child)


def _clone_node(node: _Node) -> _Node:
    n = _Node()
    n.own = _Stats.from_dict(node.own.to_dict())
    n.children = {seg: _clone_node(c) for seg, c in node.children.items()}
    return n


def _node_to_dict(node: _Node) -> dict:
    d: dict = {"s": node.own.to_dict()}
    if node.children:
        d["c"] = {seg: _node_to_dict(c) for seg, c in node.children.items()}
    return d


def _node_from_dict(d: dict) -> _Node:
    n = _Node()
    n.own = _Stats.from_dict(d.get("s", {}))
    n.children = {seg: _node_from_dict(c)
                  for seg, c in d.get("c", {}).items()}
    return n
