"""Heal sequences and drive healing.

Equivalents of the reference's admin-driven heal walks (healSequence,
cmd/admin-heal-ops.go:396), the always-on background heal
(cmd/global-heal.go:41) and new-disk auto-heal with an on-drive healing
tracker (cmd/background-newdisks-heal-ops.go).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field

from minio_tpu.storage import errors
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import service_thread
from minio_tpu.storage.local import SYSTEM_VOL, HEALING_FILE


def heal_bytes_budget() -> int:
    """Survivor-bytes-read budget per heal sequence (0 = unlimited).
    Repair reads are the hidden cost of a heal sweep on a busy cluster;
    the planner's sub-shard reads make the budget go further, and the
    budget caps how much drive/network read bandwidth one sequence may
    consume before it parks (state `budget`), to be resumed by the next
    background cycle."""
    try:
        return int(os.environ.get("MINIO_TPU_HEAL_BYTES_BUDGET", "0"))
    except ValueError:
        return 0


@dataclass
class HealSequenceStatus:
    heal_id: str = ""
    state: str = "running"   # running | finished | stopped | failed | budget
    bucket: str = ""
    prefix: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    objects_scanned: int = 0
    objects_healed: int = 0
    objects_failed: int = 0
    bytes_healed: int = 0
    # repair-planner accounting (erasure/repair.py via HealResult)
    bytes_read: int = 0             # survivor frame bytes read
    bytes_scanned: int = 0          # target residual-scan bytes
    subshard_objects: int = 0       # objects healed via ranged repair
    bytes_budget: int = 0           # 0 = unlimited
    throttle_waits: int = 0         # brownout deferrals mid-sequence
    failed_items: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "healId": self.heal_id, "state": self.state,
            "bucket": self.bucket, "prefix": self.prefix,
            "startTime": self.start_time, "endTime": self.end_time,
            "objectsScanned": self.objects_scanned,
            "objectsHealed": self.objects_healed,
            "objectsFailed": self.objects_failed,
            "bytesHealed": self.bytes_healed,
            "bytesRead": self.bytes_read,
            "bytesScanned": self.bytes_scanned,
            "subshardObjects": self.subshard_objects,
            "bytesBudget": self.bytes_budget,
            "throttleWaits": self.throttle_waits,
            "failedItems": self.failed_items[:64],
        }


class HealSequence:
    """One traversal healing every object under bucket/prefix."""

    def __init__(self, object_layer, bucket: str = "", prefix: str = "",
                 deep: bool = False, remove_dangling: bool = False,
                 throttle=None, bytes_budget: int | None = None):
        self.ol = object_layer
        self.status = HealSequenceStatus(
            heal_id=uuid.uuid4().hex, bucket=bucket, prefix=prefix,
            start_time=time.time(),
            bytes_budget=(heal_bytes_budget() if bytes_budget is None
                          else bytes_budget),
        )
        self.deep = deep
        self.remove_dangling = remove_dangling
        # brownout hook: callable -> bool; False defers the NEXT object
        # heal while foreground load is shedding (wired by the
        # BackgroundHealer / ServiceManager)
        self.throttle = throttle
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "HealSequence":
        self._thread = service_thread(
            self._run, name=f"heal-{self.status.heal_id[:8]}")
        return self

    def run_sync(self) -> HealSequenceStatus:
        self._run()
        return self.status

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float | None = None) -> None:
        if self._thread:
            self._thread.join(timeout)

    # -- traversal ----------------------------------------------------------
    def _buckets(self) -> list[str]:
        if self.status.bucket:
            return [self.status.bucket]
        names = [b["name"] if isinstance(b, dict) else b.name
                 for b in self.ol.list_buckets()]
        return [n for n in names if not n.startswith(".")]

    def _throttle_wait(self) -> None:
        """Brownout gate between object heals: while the controller says
        foreground traffic owns the IOPs, park (bounded poll) instead of
        issuing more repair reads."""
        if self.throttle is None or self.throttle():
            return
        self.status.throttle_waits += 1
        while not self._stop.is_set() and not self.throttle():
            self._stop.wait(0.25)

    def _over_budget(self) -> bool:
        b = self.status.bytes_budget
        return bool(b) and self.status.bytes_read >= b

    def _run(self) -> None:
        st = self.status
        # one trace per heal sequence (utils/tracing.py): each object
        # heal is a child span tagged with the repair planner's verdict
        # (scheme + survivor/scan bytes), so a slow sweep names WHICH
        # objects and WHICH repair scheme ate the read bandwidth
        root = tracing.start("heal.sequence", healId=st.heal_id,
                             bucket=st.bucket, prefix=st.prefix,
                             deep=self.deep)
        token = tracing.install(root) if root is not None else None
        try:
            for bucket in self._buckets():
                if self._stop.is_set():
                    st.state = "stopped"
                    break
                if self._over_budget():
                    st.state = "budget"
                    break
                try:
                    names = self.ol.list_objects(bucket, prefix=st.prefix)
                except errors.BucketNotFound:
                    continue
                for name in names:
                    if self._stop.is_set():
                        st.state = "stopped"
                        break
                    if self._over_budget():
                        # read budget spent: park — the next background
                        # cycle (or a fresh admin sequence) resumes
                        st.state = "budget"
                        break
                    self._throttle_wait()
                    st.objects_scanned += 1
                    try:
                        with tracing.span("heal.object", bucket=bucket,
                                          key=name) as sp:
                            res = self.ol.heal_object(bucket, name,
                                                      deep=self.deep)
                            if sp is not None:
                                sp.tag(
                                    scheme=getattr(res, "scheme", "full"),
                                    bytes_read=getattr(
                                        res, "bytes_read", 0),
                                    bytes_scanned=getattr(
                                        res, "bytes_scanned", 0),
                                    failed=bool(
                                        getattr(res, "failed", False)))
                        if getattr(res, "failed", False):
                            st.objects_failed += 1
                            st.failed_items.append(f"{bucket}/{name}")
                        else:
                            st.objects_healed += 1
                            st.bytes_healed += getattr(res, "object_size", 0)
                        st.bytes_read += getattr(res, "bytes_read", 0)
                        st.bytes_scanned += getattr(res, "bytes_scanned", 0)
                        if getattr(res, "scheme", "full") == "subshard":
                            st.subshard_objects += 1
                    except Exception as ex:
                        st.objects_failed += 1
                        st.failed_items.append(f"{bucket}/{name}: {ex}")
                if st.state not in ("running",):
                    break
            if st.state == "running":
                st.state = "finished"
        except Exception:
            st.state = "failed"
        finally:
            st.end_time = time.time()
            if root is not None:
                tracing.reset(token)
                root.tag(state=st.state, healed=st.objects_healed,
                         objects_failed=st.objects_failed)
                tracing.finish(root, status=200,
                               error=st.state == "failed"
                               or st.objects_failed > 0)


class HealManager:
    """Registry of heal sequences (admin-heal-ops' allHealState analogue)."""

    def __init__(self, object_layer):
        self.ol = object_layer
        self._seqs: dict[str, HealSequence] = {}
        self._mu = threading.Lock()

    def launch(self, bucket: str = "", prefix: str = "",
               deep: bool = False) -> HealSequenceStatus:
        seq = HealSequence(self.ol, bucket, prefix, deep).start()
        with self._mu:
            self._seqs[seq.status.heal_id] = seq
        return seq.status

    def get(self, heal_id: str) -> HealSequenceStatus | None:
        with self._mu:
            seq = self._seqs.get(heal_id)
        return seq.status if seq else None

    def stop(self, heal_id: str) -> bool:
        with self._mu:
            seq = self._seqs.get(heal_id)
        if not seq:
            return False
        seq.stop()
        return True

    def statuses(self) -> list[dict]:
        with self._mu:
            return [s.status.to_dict() for s in self._seqs.values()]


class BackgroundHealer:
    """Always-on periodic full-cluster heal (global-heal.go:41)."""

    def __init__(self, object_layer, interval: float = 3600.0):
        self.ol = object_layer
        self.interval = interval
        self.last_status: HealSequenceStatus | None = None
        self.cycles = 0
        # brownout hook: callable -> bool; False defers the sweep while
        # foreground load is shedding (wired by ServiceManager)
        self.throttle = None
        self._stop = threading.Event()
        self._thread = service_thread(self._run, name="bg-heal")

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if getattr(self, "_paused", False):
                continue
            if self.throttle is not None and not self.throttle():
                continue  # browned out: foreground traffic owns the IOPs
            self.heal_once()

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def heal_once(self) -> HealSequenceStatus:
        # the sequence inherits the brownout throttle (defers BETWEEN
        # object heals, not just between sweeps) and the per-sequence
        # survivor-bytes-read budget
        seq = HealSequence(self.ol, throttle=self.throttle)
        self.last_status = seq.run_sync()
        self.cycles += 1
        return self.last_status

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)


# ---------------------------------------------------------------------------
# New-disk auto-heal: healing tracker persisted on the drive so interrupted
# heals resume (cmd/background-newdisks-heal-ops.go).

def load_healing_tracker(disk) -> dict | None:
    try:
        return json.loads(disk.read_all(SYSTEM_VOL, HEALING_FILE))
    except Exception:
        return None


def save_healing_tracker(disk, tracker: dict) -> None:
    disk.write_all(SYSTEM_VOL, HEALING_FILE, json.dumps(tracker).encode())


def clear_healing_tracker(disk) -> None:
    try:
        disk.delete(SYSTEM_VOL, HEALING_FILE)
    except errors.StorageError:
        pass


def mark_disk_healing(disk) -> dict:
    tracker = {"id": uuid.uuid4().hex, "started": time.time(),
               "objects_healed": 0, "objects_failed": 0, "finished": False}
    save_healing_tracker(disk, tracker)
    return tracker


def heal_fresh_disks(pools) -> list[dict]:
    """Find drives carrying a healing tracker and re-heal their erasure
    sets onto them; returns the completed trackers."""
    done: list[dict] = []
    for pool in getattr(pools, "pools", [pools]):
        for es in pool.sets:
            trackers = {}
            fresh = []
            for d in es.disks:
                if d is None or not d.is_online():
                    continue
                t = load_healing_tracker(d)
                if t is not None:
                    trackers[id(d)] = t
                    fresh.append(d)
            if not fresh:
                continue
            # heal every bucket+object in this set
            for vol in _set_buckets(es):
                for name in _set_objects(es, vol):
                    try:
                        res = es.heal_object(vol, name)
                        ok = not getattr(res, "failed", False)
                    except Exception:
                        ok = False
                    for t in trackers.values():
                        t["objects_healed" if ok else "objects_failed"] += 1
            for d in fresh:
                t = trackers[id(d)]
                t["finished"] = True
                t["ended"] = time.time()
                clear_healing_tracker(d)
                done.append(t)
    return done


def _set_buckets(es) -> list[str]:
    """Non-system buckets visible on any online drive of one erasure set."""
    vols: set[str] = set()
    for d in es.disks:
        if d is None or not d.is_online():
            continue
        try:
            for v in d.list_volumes():
                if not v.name.startswith("."):
                    vols.add(v.name)
        except Exception:
            continue
    return sorted(vols)


def _set_objects(es, bucket: str) -> list[str]:
    try:
        return es.list_objects(bucket)
    except errors.StorageError:
        return []
