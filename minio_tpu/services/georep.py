"""Active-active geo-replication of object DATA across site peers.

Reference: cmd/bucket-replication*.go + cmd/site-replication.go — the
reference replicates object payloads between clusters with per-target
queues, MRF-style retry classification, and resumable resync.  Our
site plane (services/site.py, PR 14) converges buckets/IAM/config
only; this module closes ROADMAP item 3's payload gap: every object
VERSION written to one cluster converges to every site peer, and a
kill at any point — source worker, peer, mid-push, mid-ack — resumes
without losing or duplicating versions.

Protocol (modeled FIRST in analysis/concurrency/models/georep.py;
invariants no-version-lost, no-push-of-unacked-stale, lww-latest-is-
max, lww-convergence, wedge-freedom — six seeded mutations all yield
counterexamples):

* **discover** — a per-peer sweep worker walks the local namespace.
  The first sweep pushes everything; after that the bloom change
  tracker (utils/bloom.py) proves untouched buckets CLEAN and the
  sweep skips them (false negatives are impossible by the filter's
  contract, false positives re-push harmlessly: apply is idempotent).
  The ns_updated choke point nudges the workers so a write is pushed
  within one wakeup, not one interval.
* **push** — object versions batch into signed POSTs to the peer's
  ``/minio/admin/v3/georep/apply`` endpoint, paced by a per-peer
  inter-site bandwidth lane (utils/bandwidth.TokenBucket — the QoS
  token-bucket machinery generalized to site links).
* **ack / cursor** — the per-peer cursor (last fully-ACKed object)
  advances only after the peer's 200 landed, and is quorum-persisted
  on the first pool's drives (``georep-<peer>.json``, decom's
  seq-versioned load_state/save_state) every ``checkpoint_every``
  objects.  A killed worker resumes AFTER the last checkpoint and
  re-pushes at most the un-checkpointed window — the model's
  cursor-ahead-of-ack and resume-skips-inflight mutations are exactly
  the orderings this rules out.
* **retry / breaker** — failures classify MRF-style: *gone* (version
  deleted locally mid-push) is not a failure, *permanent* (the peer
  rejected the item) is counted and skipped, *retryable* (peer down,
  5xx, timeout) leaves the cursor where it is and trips the per-peer
  breaker after ``breaker_threshold`` consecutive failures — an open
  breaker half-opens after ``breaker_cooldown_s`` so a returned peer
  converges without ever having been hammered while down.
* **apply (receive)** — versioned ids are identity: a version the
  destination already holds answers ``already`` (idempotent re-push),
  otherwise it lands with version id + mod time + etag pinned.  Null
  versions resolve by **last-writer-wins** on (mod_time, etag) —
  mod-time first, etag as the deterministic tiebreak — and a LOSING
  incoming write answers ``stale`` instead of clobbering (the model's
  apply-clobbers-newer mutation).  Application runs with propagation
  SUPPRESSED (services/site._Suppressed) so a push can never echo
  back across sites.

Gated by ``MINIO_TPU_GEOREP`` (default off): ``S3Server.georep`` is
None, no workers, no ``minio_georep_*`` metric families, and the S3
surface is byte- and metrics-identical (pinned by
tests/test_georep.py's gate-off differential).

Knobs: ``MINIO_TPU_GEOREP_INTERVAL_S`` (sweep period, default 5),
``MINIO_TPU_GEOREP_CHECKPOINT_EVERY`` (objects per cursor save,
default 16), ``MINIO_TPU_GEOREP_BATCH_BYTES`` / ``_BATCH_OBJECTS``
(push batch bounds), ``MINIO_TPU_GEOREP_BANDWIDTH`` (per-peer
bytes/sec lane, 0 = unlimited), ``MINIO_TPU_GEOREP_BREAKER_THRESHOLD``
/ ``_BREAKER_COOLDOWN_S``, ``MINIO_TPU_GEOREP_MAX_INLINE`` (largest
version pushed inline; bigger ones are counted ``skipped_large`` —
an honest gap, not a silent one).
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import threading
import time

from minio_tpu.storage import errors
from minio_tpu.utils import tracing
from minio_tpu.utils.bandwidth import TokenBucket
from minio_tpu.utils.deadline import service_thread
from minio_tpu.utils.logger import log

from .decom import _GONE, _classify, load_state, save_state
from .site import _Suppressed, propagation_suppressed

GEOREP_APPLY_PATH = "/minio/admin/v3/georep/apply"

_TRUTHY = ("1", "on", "true", "yes")

#: geo-replication counters rendered as minio_georep_* gauges
#: (server/metrics.py); module-level so process-lifetime totals and
#: admin status agree
stats = {
    "pushed_objects": 0,      # objects fully ACKed by a peer
    "pushed_versions": 0,     # versions carried inside those pushes
    "pushed_bytes": 0,        # payload bytes shipped (pre-base64)
    "applied": 0,             # receive side: versions landed
    "already": 0,             # receive side: idempotent re-push hits
    "stale_dropped": 0,       # receive side: LWW losers not applied
    "failed_retryable": 0,
    "failed_permanent": 0,
    "gone": 0,                # versions deleted locally mid-push
    "skipped_clean_buckets": 0,
    "skipped_large": 0,       # versions over the inline size bound
    "breaker_opens": 0,
    "breaker_short_circuits": 0,
    "resyncs": 0,
    "sweeps": 0,
    "lane_waits": 0,          # pushes the bandwidth lane paced
}
_stats_mu = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _stats_mu:
        stats[key] += n


class _SweepKilled(BaseException):
    """Test-only crash injection: the push worker dies WITHOUT saving
    its cursor — the closest a thread can come to SIGKILL mid-push."""


class _PeerBreaker:
    """Consecutive-failure breaker per site peer: open after
    `threshold` straight retryable failures, half-open (one probe
    sweep allowed) after `cooldown_s`.  Same shape as utils.mrf's
    breaker, scoped to an inter-site link."""

    def __init__(self, threshold: int, cooldown_s: float):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.failures = 0
        self.open_until = 0.0
        self.opens = 0

    def allow(self) -> bool:
        if self.failures < self.threshold:
            return True
        return time.monotonic() >= self.open_until  # half-open probe

    def record_ok(self) -> None:
        self.failures = 0
        self.open_until = 0.0

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures >= self.threshold:
            if self.open_until <= time.monotonic():
                self.opens += 1
                _bump("breaker_opens")
            self.open_until = time.monotonic() + self.cooldown_s

    def state(self) -> str:
        if self.failures < self.threshold:
            return "closed"
        return "half-open" if time.monotonic() >= self.open_until \
            else "open"


class PushFailed(Exception):
    """A batch POST that did not fully land (peer down / non-200):
    the cursor stays put and the sweep ends — retryable by contract."""


class GeoRepSys:
    """Per-peer object-data push queue over the site-replication peer
    registry.  One sweep worker + bandwidth lane + breaker PER PEER
    (a down site must never stall convergence to healthy ones), one
    supervisor thread that adopts peers added after boot."""

    def __init__(self, api, site, environ=None):
        env = os.environ if environ is None else environ
        self.api = api
        self.site = site              # peer registry + credentials
        self.tracker = None           # bloom tracker, attach_tracker()
        self.interval_s = _f(env, "MINIO_TPU_GEOREP_INTERVAL_S", 5.0)
        self.checkpoint_every = max(1, _i(
            env, "MINIO_TPU_GEOREP_CHECKPOINT_EVERY", 16))
        self.batch_bytes = max(1, _i(
            env, "MINIO_TPU_GEOREP_BATCH_BYTES", 1 << 20))
        self.batch_objects = max(1, _i(
            env, "MINIO_TPU_GEOREP_BATCH_OBJECTS", 16))
        self.bandwidth = max(0, _i(env, "MINIO_TPU_GEOREP_BANDWIDTH", 0))
        self.breaker_threshold = max(1, _i(
            env, "MINIO_TPU_GEOREP_BREAKER_THRESHOLD", 3))
        self.breaker_cooldown_s = _f(
            env, "MINIO_TPU_GEOREP_BREAKER_COOLDOWN_S", 5.0)
        self.max_inline = max(1, _i(
            env, "MINIO_TPU_GEOREP_MAX_INLINE", 64 << 20))
        self._stop = threading.Event()
        self._mu = threading.Lock()
        self._workers: dict[str, threading.Thread] = {}
        self._nudges: dict[str, threading.Event] = {}
        self._lanes: dict[str, TokenBucket | None] = {}
        self._breakers: dict[str, _PeerBreaker] = {}
        self._live: dict[str, dict] = {}   # per-peer live status fields
        self._wake = threading.Event()     # supervisor wakeup
        # test-only: fn(pushed_objects) -> True kills the sweep worker
        # without a cursor save (crash injection for the chaos drill)
        self._crash_hook = None
        self._supervisor = service_thread(
            self._supervise, name="georep-supervisor")

    # ------------------------------------------------------------- gate
    @staticmethod
    def gate_enabled(environ=None) -> bool:
        env = os.environ if environ is None else environ
        return str(env.get("MINIO_TPU_GEOREP", "0")).lower() in _TRUTHY

    @classmethod
    def from_env(cls, api, site, environ=None) -> "GeoRepSys | None":
        if not cls.gate_enabled(environ):
            return None
        return cls(api, site, environ)

    def attach_tracker(self, tracker) -> None:
        """Adopt the scanner's bloom change tracker so steady-state
        sweeps skip buckets proven untouched."""
        self.tracker = tracker

    # -------------------------------------------------------- lifecycle
    def on_ns_update(self, bucket: str, obj: str) -> None:
        """ns_updated choke-point consumer: a local mutation nudges
        every push worker.  No-op while propagation is suppressed — an
        APPLIED push must not nudge a push back (the cross-site
        feedback loop the site plane's contextvar exists to kill)."""
        if propagation_suppressed():
            return
        self._wake.set()
        for ev in list(self._nudges.values()):
            ev.set()

    def nudge(self) -> None:
        self.on_ns_update("", "")

    def _supervise(self) -> None:
        """Adopt workers for every registered site peer; peers added
        after boot get a worker within one interval (or one nudge)."""
        while not self._stop.is_set():
            try:
                self._ensure_workers()
            except Exception as e:
                log.warning("georep supervisor", error=str(e))
            self._wake.wait(self.interval_s)
            self._wake.clear()

    def _ensure_workers(self) -> None:
        with self.site._mu:
            names = list(self.site.peers)
        for name in names:
            with self._mu:
                t = self._workers.get(name)
                if t is not None and t.is_alive():
                    continue
                if name not in self._nudges:
                    self._nudges[name] = threading.Event()
                if name not in self._lanes:
                    self._lanes[name] = TokenBucket(self.bandwidth) \
                        if self.bandwidth > 0 else None
                if name not in self._breakers:
                    self._breakers[name] = _PeerBreaker(
                        self.breaker_threshold, self.breaker_cooldown_s)
                t = service_thread(self._worker, name, start=False,
                                   name=f"georep-{name}")
                self._workers[name] = t
            t.start()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        for ev in list(self._nudges.values()):
            ev.set()
        if self._supervisor is not None:
            self._supervisor.join(2)
        with self._mu:
            workers = list(self._workers.values())
        for t in workers:
            t.join(2)

    # ----------------------------------------------------------- cursor
    def _state_pool(self):
        return self.api.pools[0]

    def _load(self, peer_name: str) -> dict:
        st = load_state(self._state_pool(), f"georep-{peer_name}.json")
        if "initial_synced" not in st:
            st = {"state": "new", "initial_synced": False,
                  "done_buckets": [], "cursor": None,
                  "pushed_objects": 0, "pushed_versions": 0, "seq": 0}
        return st

    def _save(self, peer_name: str, st: dict) -> None:
        """Quorum-persist the cursor; a miss marks the peer's status
        degraded instead of silently continuing unpersisted."""
        st["degraded"] = not save_state(
            self._state_pool(), st, f"georep-{peer_name}.json")

    # ----------------------------------------------------------- worker
    def _worker(self, peer_name: str) -> None:
        ev = self._nudges[peer_name]
        br = self._breakers[peer_name]
        while not self._stop.is_set():
            ev.wait(self.interval_s)
            ev.clear()
            if self._stop.is_set():
                return
            with self.site._mu:
                peer = self.site.peers.get(peer_name)
            if peer is None:
                return  # peer removed: drop its worker
            if not br.allow():
                _bump("breaker_short_circuits")
                self._set_live(peer_name, breaker=br.state())
                continue
            try:
                self._sweep(peer)
            except _SweepKilled:
                return  # crash injection: NO cursor save
            except PushFailed as e:
                br.record_failure()
                self._set_live(peer_name, breaker=br.state(),
                               lastError=str(e))
            except Exception as e:
                br.record_failure()
                _bump("failed_retryable")
                log.warning("georep sweep failed", peer=peer_name,
                            error=str(e))
                self._set_live(peer_name, breaker=br.state(),
                               lastError=str(e))

    def _set_live(self, peer_name: str, **kv) -> None:
        with self._mu:
            self._live.setdefault(peer_name, {}).update(kv)

    def _sweep(self, peer) -> None:
        """One push sweep to one peer: full namespace on the first run,
        bloom-filtered after; cursor-resumed within the in-flight
        bucket."""
        st = self._load(peer.name)
        full = not st.get("initial_synced")
        root = tracing.start("georep.sweep", peer=peer.name,
                             full=bool(full))
        token = tracing.install(root) if root is not None else None
        t0 = time.monotonic()
        status = 200
        _bump("sweeps")
        skipped = 0
        try:
            st["state"] = "syncing"
            for vol in sorted(self.api.list_buckets(),
                              key=lambda v: v.name):
                bucket = vol.name
                if self._stop.is_set():
                    self._save(peer.name, st)
                    return
                if bucket in st["done_buckets"]:
                    continue
                if not full and self.tracker is not None \
                        and not self.tracker.bucket_dirty(bucket):
                    skipped += 1
                    _bump("skipped_clean_buckets")
                    continue
                with tracing.span("georep.bucket", bucket=bucket,
                                  peer=peer.name):
                    self._sync_bucket(peer, bucket, st)
                st["done_buckets"].append(bucket)
                st["cursor"] = None
                self._save(peer.name, st)
            # sweep complete: from here on the bloom filter owns delta
            # discovery, and the next sweep starts a fresh bucket walk
            st["initial_synced"] = True
            st["done_buckets"] = []
            st["cursor"] = None
            st["state"] = "idle"
            st["last_sweep"] = time.time()
            self._save(peer.name, st)
            self._breakers[peer.name].record_ok()
            self._set_live(peer.name, breaker="closed", lastError=None,
                           skippedClean=skipped)
        except PushFailed:
            status = 503
            # cursor stays where the last ACK left it; persist progress
            # so a process kill during the outage resumes identically
            st["state"] = "retrying"
            self._save(peer.name, st)
            raise
        except _SweepKilled:
            status = 500
            raise  # crash injection: NO save (simulated SIGKILL)
        finally:
            if root is not None:
                root.tag(skippedClean=skipped)
                tracing.reset(token)
                tracing.finish(root, status=status, error=status >= 500,
                               duration=time.monotonic() - t0)

    def _sync_bucket(self, peer, bucket: str, st: dict) -> None:
        cur = st.get("cursor") or {}
        start_after = cur.get("obj", "") if cur.get("bucket") == bucket \
            else ""
        batch: list[dict] = []
        batch_names: list[str] = []
        batch_bytes = 0
        since_ckpt = 0

        def flush() -> None:
            nonlocal batch, batch_names, batch_bytes, since_ckpt
            if not batch:
                return
            self._push_batch(peer, bucket, batch)
            # the peer's 200 IS the ack: only now may the cursor pass
            # these objects (the model's ack_{d} action — cursor+=1
            # strictly after wire hit "applied")
            st["cursor"] = {"bucket": bucket, "obj": batch_names[-1]}
            st["pushed_objects"] += len(batch_names)
            st["pushed_versions"] += len(batch)
            _bump("pushed_objects", len(batch_names))
            since_ckpt += len(batch_names)
            batch, batch_names, batch_bytes = [], [], 0
            if since_ckpt >= self.checkpoint_every:
                since_ckpt = 0
                self._save(peer.name, st)

        for entry in self.api.list_entries(bucket):
            if self._stop.is_set():
                flush()
                self._save(peer.name, st)
                return
            name = entry.name
            if start_after and name <= start_after:
                continue  # ACKed before the kill/restart
            if self._crash_hook is not None \
                    and self._crash_hook(st["pushed_objects"]):
                raise _SweepKilled()
            items, nbytes = self._object_items(bucket, entry)
            if items:
                batch.extend(items)
                batch_names.append(name)
                batch_bytes += nbytes
            else:
                # nothing pushable (all gone / over inline bound): the
                # cursor may still pass it once prior pushes ACKed
                if not batch:
                    st["cursor"] = {"bucket": bucket, "obj": name}
            if batch_bytes >= self.batch_bytes \
                    or len(batch_names) >= self.batch_objects:
                flush()
        flush()
        self._save(peer.name, st)

    def _object_items(self, bucket: str, entry
                      ) -> tuple[list[dict], int]:
        """Wire items for every version of one object, oldest first so
        the peer's xl.meta ordering (and is_latest) lands identically;
        reads that race a local delete classify `gone` and drop out."""
        items: list[dict] = []
        nbytes = 0
        for oi in reversed(entry.versions):
            try:
                item = {
                    "bucket": bucket, "obj": entry.name,
                    "versionId": oi.version_id or "",
                    "modTime": oi.mod_time,
                    "etag": oi.etag or oi.metadata.get("etag", ""),
                }
                if oi.delete_marker:
                    item["deleteMarker"] = True
                else:
                    if max(oi.size, 0) > self.max_inline:
                        _bump("skipped_large")
                        continue
                    _, stream = self.api.get_object(
                        bucket, entry.name, version_id=oi.version_id)
                    data = b"".join(stream)
                    item["data"] = base64.b64encode(data).decode()
                    item["size"] = len(data)
                    item["contentType"] = oi.content_type
                    item["userMeta"] = {
                        k: v for k, v in oi.metadata.items()
                        if k not in ("etag", "content-type")}
                    nbytes += len(data)
                items.append(item)
            except _GONE:
                _bump("gone")
                continue
            except Exception as e:
                kind = _classify(e)
                if kind == "gone":
                    _bump("gone")
                    continue
                _bump("failed_%s" % ("permanent" if kind == "permanent"
                                     else "retryable"))
                if kind != "permanent":
                    raise PushFailed(
                        f"read {bucket}/{entry.name}: {e}") from e
        return items, nbytes

    def _push_batch(self, peer, bucket: str, items: list[dict]) -> None:
        body_doc = {"items": items}
        body = json.dumps(body_doc).encode()
        lane = self._lanes.get(peer.name)
        if lane is not None:
            wait = lane.debit(len(body))
            if wait > 0:
                _bump("lane_waits")
                if self._stop.wait(wait):
                    raise PushFailed("shutdown mid-pacing")
        t0 = time.monotonic()
        with tracing.span("georep.push", peer=peer.name, bucket=bucket,
                          items=len(items), bytes=len(body)):
            results = self._post(peer, body)
        self._breakers[peer.name].record_ok()
        applied = already = stale = perm = 0
        for r in results:
            s = r.get("status")
            if s == "applied":
                applied += 1
            elif s == "already":
                already += 1
            elif s == "stale":
                stale += 1
            elif r.get("retryable", True):
                # a per-item retryable failure keeps the cursor behind
                # this batch: the whole batch re-pushes (idempotent)
                raise PushFailed(
                    f"peer {peer.name} item failed: "
                    f"{r.get('error', 'unknown')}")
            else:
                perm += 1
        nbytes = sum(i.get("size", 0) for i in items)
        _bump("pushed_versions", len(items))
        _bump("pushed_bytes", nbytes)
        if perm:
            _bump("failed_permanent", perm)
        self._set_live(peer.name, lastPushMs=round(
            (time.monotonic() - t0) * 1e3, 3), breaker="closed")

    def _post(self, peer, body: bytes) -> list[dict]:
        """Signed POST of one batch to the peer's apply endpoint (the
        site plane's wire idiom); non-200 raises PushFailed —
        retryable by contract, the breaker owns the backoff."""
        from minio_tpu.server import sigv4

        ep = peer.endpoint
        tls = ep.startswith("https://")
        netloc = ep.split("://", 1)[-1].rstrip("/")
        headers = {"host": netloc, "content-type": "application/json"}
        signed = sigv4.sign_request("POST", GEOREP_APPLY_PATH, [],
                                    headers, body, peer.access_key,
                                    peer.secret_key)
        host, _, port = netloc.partition(":")
        cls = http.client.HTTPSConnection if tls \
            else http.client.HTTPConnection
        conn = cls(host, int(port or (443 if tls else 80)), timeout=15)
        try:
            conn.request("POST", GEOREP_APPLY_PATH, body=body,
                         headers=signed)
            resp = conn.getresponse()
            data = resp.read()
        except OSError as e:
            raise PushFailed(f"peer {peer.name} unreachable: {e}") from e
        finally:
            conn.close()
        if resp.status != 200:
            raise PushFailed(
                f"peer {peer.name} returned {resp.status}: "
                f"{data[:200]!r}")
        try:
            return json.loads(data).get("results", [])
        except ValueError as e:
            raise PushFailed(
                f"peer {peer.name} sent malformed ack") from e

    # ---------------------------------------------------------- receive
    def apply(self, doc: dict) -> dict:
        """Apply one pushed batch from a peer site.  Runs with
        propagation suppressed: landing a version must not re-push it
        (cross-site loop) nor nudge our own workers."""
        items = doc.get("items")
        if not isinstance(items, list):
            raise ValueError("georep apply: 'items' list required")
        results = []
        with _Suppressed():
            for item in items:
                try:
                    results.append({"status": self._apply_item(item)})
                except Exception as e:
                    kind = _classify(e) if isinstance(e, Exception) \
                        else "retryable"
                    results.append({
                        "status": "error", "error": str(e),
                        "retryable": kind != "permanent"})
        return {"results": results}

    def _apply_item(self, item: dict) -> str:
        bucket = item["bucket"]
        name = item["obj"]
        version_id = item.get("versionId") or ""
        mod_time = item.get("modTime")
        etag = item.get("etag", "")
        if not self.api.bucket_exists(bucket):
            # the site plane converges bucket metadata; data arriving
            # first must not bounce on a not-yet-created bucket
            try:
                self.api.make_bucket(bucket)
            except errors.StorageError:
                pass
        if item.get("deleteMarker"):
            if self._has_version(bucket, name, version_id, mod_time,
                                 etag, marker=True):
                _bump("already")
                return "already"
            self.api.put_delete_marker(bucket, name, version_id,
                                       mod_time)
            _bump("applied")
            return "applied"
        if version_id:
            if self._has_version(bucket, name, version_id, mod_time,
                                 etag):
                _bump("already")
                return "already"
            self._put_pinned(bucket, name, item, versioned=True)
            _bump("applied")
            return "applied"
        # null version: versioned ids are identity, null versions are
        # a SLOT — last-writer-wins on (mod_time, etag), etag breaking
        # mod-time ties deterministically (both sites order any pair
        # of writes identically: the model's _lww_max)
        local = self._null_info(bucket, name)
        if local is not None:
            lk = (local.mod_time or 0,
                  local.etag or local.metadata.get("etag", ""))
            ik = (mod_time or 0, etag)
            if lk == ik:
                _bump("already")
                return "already"
            if lk > ik:
                _bump("stale_dropped")
                return "stale"
        self._put_pinned(bucket, name, item, versioned=False)
        _bump("applied")
        return "applied"

    def _has_version(self, bucket: str, name: str, version_id: str,
                     mod_time, etag: str, marker: bool = False) -> bool:
        from minio_tpu.erasure.objects import MethodNotAllowedDeleteMarker

        try:
            info = self.api.get_object_info(bucket, name,
                                            version_id=version_id)
        except MethodNotAllowedDeleteMarker:
            return True  # the id exists locally (as a marker)
        except (errors.StorageError, errors.MethodNotAllowed):
            return False
        if not version_id and not marker:
            # null slot: exact-copy check only — LWW decides the rest
            return (info.mod_time or 0) == (mod_time or 0) and \
                (info.etag or info.metadata.get("etag", "")) == etag
        return True

    def _null_info(self, bucket: str, name: str):
        from minio_tpu.erasure.objects import MethodNotAllowedDeleteMarker

        try:
            return self.api.get_object_info(bucket, name)
        except MethodNotAllowedDeleteMarker as e:
            return e.object_info
        except (errors.StorageError, errors.MethodNotAllowed):
            return None

    def _put_pinned(self, bucket: str, name: str, item: dict,
                    versioned: bool) -> None:
        import io

        from minio_tpu.erasure.objects import PutObjectOptions

        data = base64.b64decode(item.get("data", ""))
        opts = PutObjectOptions(
            user_metadata=dict(item.get("userMeta") or {}),
            content_type=item.get("contentType", ""),
            versioned=versioned,
            version_id=item.get("versionId") or None,
            mod_time=item.get("modTime"),
            # the ETag crosses sites verbatim: multipart/SSE ETags
            # recomputed from the pushed stream would differ and break
            # If-Match against the replica
            etag=item.get("etag", ""),
        )
        self.api.put_object(bucket, name, io.BytesIO(data), len(data),
                            opts)

    # ------------------------------------------------------------ admin
    def resync(self, peer_name: str, full: bool = True) -> dict:
        """Reset one peer's cursor so the next sweep re-walks (and
        re-pushes — idempotently) the namespace; `mc admin replicate
        resync` for payload data."""
        with self.site._mu:
            if peer_name not in self.site.peers:
                raise KeyError(peer_name)
        st = self._load(peer_name)
        if full:
            st["initial_synced"] = False
        st["done_buckets"] = []
        st["cursor"] = None
        st["state"] = "resync-pending"
        self._save(peer_name, st)
        _bump("resyncs")
        self._wake.set()
        ev = self._nudges.get(peer_name)
        if ev is not None:
            ev.set()
        return {"peer": peer_name, "full": bool(full)}

    def status(self) -> dict:
        with self.site._mu:
            names = list(self.site.peers)
        peers = {}
        for name in names:
            st = self._load(name)
            br = self._breakers.get(name)
            with self._mu:
                live = dict(self._live.get(name, {}))
            worker = self._workers.get(name)
            peers[name] = {
                "state": st.get("state", "new"),
                "initialSynced": bool(st.get("initial_synced")),
                "cursor": st.get("cursor"),
                "doneBuckets": len(st.get("done_buckets", [])),
                "pushedObjects": st.get("pushed_objects", 0),
                "pushedVersions": st.get("pushed_versions", 0),
                "degraded": bool(st.get("degraded")),
                "breaker": br.state() if br is not None else "closed",
                "breakerOpens": br.opens if br is not None else 0,
                "workerAlive": bool(worker is not None
                                    and worker.is_alive()),
                **live,
            }
        with _stats_mu:
            totals = dict(stats)
        return {"enabled": True, "intervalSeconds": self.interval_s,
                "checkpointEvery": self.checkpoint_every,
                "bandwidth": self.bandwidth, "peers": peers,
                "totals": totals}


def _f(env, key: str, default: float) -> float:
    try:
        return float(env.get(key, str(default)))
    except (TypeError, ValueError):
        return default


def _i(env, key: str, default: int) -> int:
    try:
        return int(float(env.get(key, str(default))))
    except (TypeError, ValueError):
        return default
