"""Brownout controller: background services yield under foreground load.

When the API admission queue deepens or requests start shedding, the
scanner, background heal and the MRF queue are throttled so every drive
IOP serves a waiting client; when the pressure drains for
`release_after` seconds they resume.  The reference reaches the same
end through per-op IO throttling of the scanner
(cmd/data-scanner.go scannerSleeper + maxIO dynamics); a single
engage/release controller keeps the policy observable: one gauge says
whether the cluster is browned out and two counters say how often.

Event-driven by design — no thread of its own.  The API front calls
`note_pressure`/`note_shed` as load arrives; background loops poll
`background_allowed()` before each unit of work, and that poll performs
the time-based release check, so a cluster that goes fully idle (no
more foreground calls) still releases on the next background tick.
"""

from __future__ import annotations

import threading
import time


class BrownoutController:
    def __init__(self, engage_depth: int = 8, release_after: float = 5.0):
        self.engage_depth = engage_depth    # admission waiters that engage
        self.release_after = release_after  # quiet seconds before release
        self._mu = threading.Lock()
        self._engaged = False
        self._forced = False   # held engaged by the overload controller
        self._last_pressure = 0.0
        self.engagements = 0
        self.releases = 0
        self.sheds_seen = 0
        self.deferrals = 0
        self.hot_bypasses = 0
        self.forced_engagements = 0

    # -- pressure inputs (API front) ----------------------------------------
    def note_pressure(self, queue_depth: int) -> None:
        """Called per admission attempt with the current waiter count."""
        if queue_depth >= self.engage_depth:
            self._pressure()

    def note_shed(self) -> None:
        """A request was shed with 503: unconditional pressure."""
        with self._mu:
            self.sheds_seen += 1
        self._pressure()

    def note_hot_bypass(self) -> None:
        """A probable hot-cache hit was admitted through the dedicated
        fast lane while the API lane was saturated.  RAM-served reads
        spend no drive IOPs, so they are deliberately NOT pressure —
        background work must keep running while a hot flood is absorbed
        from memory — but the count keeps that economics decision
        observable next to engagements/sheds."""
        with self._mu:
            self.hot_bypasses += 1

    def _pressure(self) -> None:
        with self._mu:
            self._last_pressure = time.monotonic()
            if not self._engaged:
                self._engaged = True
                self.engagements += 1

    # -- controller actuation (server/controller.py, ISSUE 18) --------------
    def force(self, on: bool) -> None:
        """Hold the brownout engaged regardless of API pressure — the
        overload controller sheds background work on fast-window SLO
        burn the pressure heuristics haven't seen yet.  Releasing the
        force does NOT release the brownout directly: the normal
        time-based release path clears it on the next poll, so the two
        control inputs compose instead of fighting."""
        with self._mu:
            if on and not self._forced:
                self._forced = True
                self.forced_engagements += 1
                if not self._engaged:
                    self._engaged = True
                    self.engagements += 1
            elif not on:
                self._forced = False

    # -- queries (background services) --------------------------------------
    def engaged(self) -> bool:
        with self._mu:
            self._check_release_locked()
            return self._engaged

    def background_allowed(self) -> bool:
        """False while browned out; each refusal counts as a deferral."""
        with self._mu:
            self._check_release_locked()
            if self._engaged:
                self.deferrals += 1
                return False
            return True

    def _check_release_locked(self) -> None:
        if self._engaged and not self._forced and \
                time.monotonic() - self._last_pressure >= self.release_after:
            self._engaged = False
            self.releases += 1

    def stats(self) -> dict:
        with self._mu:
            self._check_release_locked()
            return {
                "engaged": self._engaged,
                "forced": self._forced,
                "forcedEngagements": self.forced_engagements,
                "engagements": self.engagements,
                "releases": self.releases,
                "shedsSeen": self.sheds_seen,
                "deferrals": self.deferrals,
                "hotBypasses": self.hot_bypasses,
                "engageDepth": self.engage_depth,
                "releaseAfter": self.release_after,
            }
