"""Data scanner + data-usage accounting.

Equivalent of the reference's continuous scanner (runDataScanner,
cmd/data-scanner.go:97) and hierarchical usage cache
(cmd/data-usage-cache.go): walks every erasure set, aggregates per-bucket
usage (objects, versions, bytes, size histogram), triggers heal for
objects with missing shards, and evaluates lifecycle actions via a
pluggable callback.  The usage cache is persisted in the system volume so
admin/metrics queries don't rescan.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from minio_tpu.storage import errors
from minio_tpu.utils.deadline import service_thread
from minio_tpu.storage.local import SYSTEM_VOL

USAGE_CACHE_FILE = "data-usage.json"
TREE_CACHE_FILE = "data-usage-tree.json"

# size histogram buckets, reference sizeHistogram (cmd/data-usage-cache.go)
SIZE_BUCKETS = [
    ("LESS_THAN_1024_B", 1024),
    ("BETWEEN_1024_B_AND_1_MB", 1024 * 1024),
    ("BETWEEN_1_MB_AND_10_MB", 10 * 1024 * 1024),
    ("BETWEEN_10_MB_AND_64_MB", 64 * 1024 * 1024),
    ("BETWEEN_64_MB_AND_128_MB", 128 * 1024 * 1024),
    ("BETWEEN_128_MB_AND_512_MB", 512 * 1024 * 1024),
    ("GREATER_THAN_512_MB", float("inf")),
]


def _histogram_bucket(size: int) -> str:
    for name, limit in SIZE_BUCKETS:
        if size < limit:
            return name
    return SIZE_BUCKETS[-1][0]


@dataclass
class BucketUsage:
    objects: int = 0
    versions: int = 0
    delete_markers: int = 0
    size: int = 0
    histogram: dict = field(default_factory=dict)

    def add(self, size: int, versions: int = 1, delete_markers: int = 0) -> None:
        self.objects += 1
        self.versions += versions
        self.delete_markers += delete_markers
        self.size += size
        b = _histogram_bucket(size)
        self.histogram[b] = self.histogram.get(b, 0) + 1

    def to_dict(self) -> dict:
        return {"objects": self.objects, "versions": self.versions,
                "deleteMarkers": self.delete_markers, "size": self.size,
                "histogram": self.histogram}

    @classmethod
    def from_dict(cls, d: dict) -> "BucketUsage":
        u = cls(objects=d.get("objects", 0), versions=d.get("versions", 0),
                delete_markers=d.get("deleteMarkers", 0),
                size=d.get("size", 0))
        u.histogram = dict(d.get("histogram", {}))
        return u


@dataclass
class DataUsageInfo:
    buckets: dict = field(default_factory=dict)   # bucket -> BucketUsage
    last_update: float = 0.0
    objects_scanned: int = 0
    heals_triggered: int = 0
    lifecycle_actions: int = 0
    lifecycle_errors: int = 0

    def total_size(self) -> int:
        return sum(u.size for u in self.buckets.values())

    def total_objects(self) -> int:
        return sum(u.objects for u in self.buckets.values())

    def to_dict(self) -> dict:
        return {
            "lastUpdate": self.last_update,
            "objectsTotalCount": self.total_objects(),
            "objectsTotalSize": self.total_size(),
            "objectsScanned": self.objects_scanned,
            "healsTriggered": self.heals_triggered,
            "lifecycleActions": self.lifecycle_actions,
            "lifecycleErrors": self.lifecycle_errors,
            "bucketsUsage": {b: u.to_dict() for b, u in self.buckets.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DataUsageInfo":
        info = cls(last_update=d.get("lastUpdate", 0.0),
                   objects_scanned=d.get("objectsScanned", 0),
                   heals_triggered=d.get("healsTriggered", 0),
                   lifecycle_actions=d.get("lifecycleActions", 0),
                   lifecycle_errors=d.get("lifecycleErrors", 0))
        info.buckets = {b: BucketUsage.from_dict(u)
                        for b, u in d.get("bucketsUsage", {}).items()}
        return info


class DataScanner:
    """Periodic scan of all sets: usage accounting + heal + lifecycle.

    lifecycle_fn(bucket, object_info) -> bool is called per scanned object
    version; returning True means the version was removed (expired /
    transitioned) and should not be counted.
    """

    def __init__(self, pools, interval: float = 60.0,
                 heal_queue=None, lifecycle_fn=None, autostart: bool = True,
                 tracker=None, bitrot_cycle: int = 0):
        self.pools = pools
        self.interval = interval
        self.heal_queue = heal_queue
        self.lifecycle_fn = lifecycle_fn
        self.tracker = tracker  # DataUpdateTracker; None -> always walk
        # every Nth cycle enqueues bitrot-VERIFYING heals for the objects
        # it walks (reference `bitrotscan on` scanner mode,
        # cmd/data-scanner.go healDeepScan / internal/config/heal).
        # 0 = off (the reference default: deep scans cost full reads).
        if bitrot_cycle == 0:
            bitrot_cycle = int(os.environ.get(
                "MINIO_TPU_SCANNER_BITROT_CYCLE", "0") or 0)
        self.bitrot_cycle = bitrot_cycle
        self.deep_heals_queued = 0
        self.buckets_skipped = 0
        self.subtree_rescans = 0  # bounded (non-full) bucket walks
        # dirty-subtree rescans whose name enumeration was served by
        # the drives' metadata index instead of directory walks
        # (ISSUE 17: bloom picks the prefixes, the index enumerates)
        self.index_passes = 0
        # brownout hook: callable -> bool; False defers the cycle while
        # foreground load is shedding (wired by ServiceManager)
        self.throttle = None
        self.usage = DataUsageInfo()
        # hierarchical usage: per-set trees (persisted per set) + the
        # cross-set/pool merge served to admin queries
        # (cmd/data-usage-cache.go)
        self._set_trees: dict = {}   # (pool_idx, set_idx) -> {bucket: tree}
        self._trees: dict = {}       # bucket -> merged UsageTree
        self.cycles = 0
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if autostart:
            self._thread = service_thread(self._run, name="data-scanner")

    # -- loop ---------------------------------------------------------------
    def _run(self) -> None:
        # initial usage from the persisted cache so restarts serve stats
        cached = self._load_cache()
        if cached is not None:
            with self._mu:
                self.usage = cached
        try:
            self._load_set_trees()
        except Exception:
            pass
        while not self._stop.wait(self.interval):
            if getattr(self, "_paused", False):
                continue
            if self.throttle is not None and not self.throttle():
                continue  # browned out: skip the cycle, retry next tick
            try:
                self.scan_cycle()
            except Exception:
                pass

    def pause(self) -> None:
        """Freeze cycles without tearing the thread down (peer
        signal-service stop-services, cmd/peer-rest-client.go:683)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def close(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    # -- one full cycle ------------------------------------------------------
    # in-progress uploads older than this are reclaimed even without a
    # lifecycle rule (reference cleanupStaleUploads default expiry,
    # cmd/erasure-sets.go:489)
    STALE_UPLOAD_EXPIRY = 24 * 3600.0

    def _cleanup_stale_uploads(self, es, info: DataUsageInfo) -> None:
        """ONE multipart walk per set per cycle; per-bucket lifecycle
        abort rules + the global stale expiry; orphaned upload dirs
        (unreadable/legacy metadata) are reclaimed once stale."""
        lf = self.lifecycle_fn
        try:
            uploads = es.enumerate_multipart_uploads()
        except Exception:
            return
        now = time.time()
        lc_cache: dict = {}
        for up in uploads:
            if not up.bucket:
                # orphan: no recoverable key — remove the raw dir when
                # old enough (initiated 0.0 = unreadable everywhere:
                # treat as stale)
                if now - up.initiated > self.STALE_UPLOAD_EXPIRY:
                    d0 = up.metadata.get("__dir", "")
                    for d in es.disks:
                        if d is None or not d.is_online() or not d0:
                            continue
                        try:
                            d.delete(SYSTEM_VOL, d0, recursive=True)
                        except Exception:
                            continue
                    info.lifecycle_actions += 1
                continue
            lc = lc_cache.get(up.bucket, False)
            if lc is False:
                lc = None
                if lf is not None and getattr(lf, "meta", None) is not None:
                    try:
                        lc = lf.meta.lifecycle(up.bucket)
                    except Exception:
                        lc = None
                lc_cache[up.bucket] = lc
            limit = self.STALE_UPLOAD_EXPIRY
            if lc is not None:
                days = lc.abort_multipart_days(up.object)
                if days > 0:
                    limit = min(limit, days * 86400.0)
            if up.initiated and now - up.initiated > limit:
                try:
                    es.abort_multipart_upload(up.bucket, up.object,
                                              up.upload_id)
                    info.lifecycle_actions += 1
                except Exception:
                    continue

    def scan_cycle(self) -> DataUsageInfo:
        from .usage_tree import UsageTree

        info = DataUsageInfo(last_update=time.time())
        merged: dict[str, UsageTree] = {}
        for pool in getattr(self.pools, "pools", [self.pools]):
            for es in pool.sets:
                key = (getattr(es, "pool_index", 0),
                       getattr(es, "set_index", 0))
                set_trees = self._scan_set(es, info)
                self._set_trees[key] = set_trees
                self._persist_set_trees(es, set_trees)
                for bucket, tree in set_trees.items():
                    m = merged.get(bucket)
                    if m is None:
                        merged[bucket] = m = UsageTree()
                    m.merge(tree)
        info.buckets = {
            b: BucketUsage.from_dict(t.totals()) for b, t in merged.items()
        }
        with self._mu:
            self.usage = info
            self._trees = merged
        self.cycles += 1
        if self.tracker is not None:
            self.tracker.cycle()
        self._save_cache(info)
        return info

    def _top_level_entries(self, es, bucket: str) -> set[str]:
        """Top-level names in one set's bucket — one readdir per drive,
        no recursion (discovers folders created since the last cycle)."""
        out: set[str] = set()
        for d in es.disks:
            if d is None:
                continue
            try:
                if not d.is_online():
                    continue
                for name in d.list_dir(bucket, ""):
                    out.add(name.rstrip("/"))
            except Exception:
                continue
        return out

    def _scan_object(self, es, bucket: str, name: str,
                     info: DataUsageInfo, tree) -> None:
        """One object's health + lifecycle + usage accounting."""
        info.objects_scanned += 1
        try:
            fi, missing = es.object_health(bucket, name)
        except errors.StorageError:
            # unreadable object: a heal attempt may still recover
            # or purge a dangling entry
            if self.heal_queue:
                self.heal_queue(bucket, name, "")
                info.heals_triggered += 1
            return
        if missing and self.heal_queue:
            self.heal_queue(bucket, name, fi.version_id)
            info.heals_triggered += 1
        elif self.heal_queue and self.bitrot_cycle > 0 \
                and (self.cycles + 1) % self.bitrot_cycle == 0:
            # deep cycle: verify every shard's interleaved hashes, not
            # just presence/size (silent corruption is invisible to the
            # shallow check) — reference healDeepScan when bitrotscan on
            self.heal_queue(bucket, name, fi.version_id, deep=True)
            self.deep_heals_queued += 1
        # lifecycle evaluation
        if self.lifecycle_fn is not None:
            try:
                from minio_tpu.erasure.objects import ObjectInfo
                oi = ObjectInfo.from_file_info(fi, bucket, name, True)
                if self.lifecycle_fn(bucket, oi):
                    info.lifecycle_actions += 1
                    return
            except Exception:
                # evaluation failures must not stop the scan, but a
                # silently-broken ILM pipeline must be observable
                info.lifecycle_errors += 1
        if fi.deleted:
            tree.add(name, 0, versions=0, delete_markers=1)
        else:
            tree.add(name, fi.size)

    def _scan_set(self, es, info: DataUsageInfo) -> dict:
        """-> {bucket: UsageTree} for this set.  Three speeds per bucket
        (cmd/data-scanner.go:368 + cmd/data-update-tracker.go):
        clean bucket -> reuse the previous tree outright; dirty bucket
        with a usable tree -> rescan ONLY the dirty top-level subtrees
        and splice them in; otherwise -> full walk."""
        from .heal import _set_buckets
        from .usage_tree import UsageTree

        self._cleanup_stale_uploads(es, info)
        key = (getattr(es, "pool_index", 0), getattr(es, "set_index", 0))
        prev = self._set_trees.get(key, {})
        out: dict = {}
        deep = self.bitrot_cycle > 0 \
            and (self.cycles + 1) % self.bitrot_cycle == 0
        for bucket in _set_buckets(es):
            ptree = prev.get(bucket)
            # a deep (bitrot) cycle must walk everything — clean-bucket
            # reuse and subtree resume would skip the verification
            tracked = not deep and self.tracker is not None \
                and self.tracker.history is not None
            if tracked and ptree is not None \
                    and not self.tracker.bucket_dirty(bucket):
                # bloom filter proves no write touched the bucket since
                # the last cycle: reuse its tree, skip the drive walk
                out[bucket] = ptree
                self.buckets_skipped += 1
                continue
            if tracked and ptree is not None \
                    and ptree.root.own.objects == 0:
                # bounded rescan: only top-level segments the tracker
                # cannot prove clean are re-walked; the rest of the tree
                # carries over (kills VERDICT r3 weak #5)
                tree = ptree.clone()
                segs = set(tree.top_segments()) \
                    | self._top_level_entries(es, bucket)
                dirty = sorted(
                    s for s in segs
                    if self.tracker.prefix_dirty(bucket, s))
                # dirty-prefix enumeration rides the metadata index
                # when any drive can serve it (union_walk probes
                # per-drive index_names before walking)
                indexed = any(
                    getattr(d, "index_available", None) is not None
                    and d.index_available(bucket)
                    for d in es.disks if d is not None)
                temp = UsageTree()
                seen: set[str] = set()
                ok = True
                for seg in dirty:
                    try:
                        names = es.list_objects(bucket, seg)
                    except errors.StorageError:
                        ok = False
                        break
                    for name in names:
                        if name not in seen:
                            seen.add(name)
                            self._scan_object(es, bucket, name, info, temp)
                if ok:
                    for seg in set(dirty) | set(temp.top_segments()):
                        temp_sub = temp
                        tree.replace_top(seg, temp_sub)
                    out[bucket] = tree
                    self.subtree_rescans += 1
                    if indexed:
                        self.index_passes += 1
                    continue
            # full walk
            tree = UsageTree()
            try:
                names = es.list_objects(bucket)
            except errors.StorageError:
                out[bucket] = tree
                continue
            for name in names:
                self._scan_object(es, bucket, name, info, tree)
            out[bucket] = tree
        return out

    # -- persistence ----------------------------------------------------------
    def _cache_disk(self):
        for pool in getattr(self.pools, "pools", [self.pools]):
            for es in pool.sets:
                for d in es.disks:
                    if d is not None and d.is_online():
                        return d
        return None

    def _persist_set_trees(self, es, set_trees: dict) -> None:
        """One tree file per SET, on its first online drive — restart
        recovers exact per-folder usage without a rescan (reference
        persists dataUsageCache per drive, cmd/data-usage-cache.go)."""
        for d in es.disks:
            if d is None:
                continue
            try:
                if not d.is_online():
                    continue
                d.write_all(SYSTEM_VOL, TREE_CACHE_FILE, json.dumps({
                    b: t.to_dict() for b, t in set_trees.items()
                }).encode())
                return
            except Exception:
                continue

    def _load_set_trees(self) -> None:
        from .usage_tree import UsageTree

        merged: dict = {}
        for pool in getattr(self.pools, "pools", [self.pools]):
            for es in pool.sets:
                key = (getattr(es, "pool_index", 0),
                       getattr(es, "set_index", 0))
                doc = None
                for d in es.disks:
                    if d is None:
                        continue
                    try:
                        doc = json.loads(
                            d.read_all(SYSTEM_VOL, TREE_CACHE_FILE))
                        break
                    except Exception:
                        continue
                if doc is None:
                    continue
                trees = {}
                try:
                    for b, td in doc.items():
                        trees[b] = UsageTree.from_dict(td)
                except Exception:
                    continue
                self._set_trees[key] = trees
                for b, t in trees.items():
                    m = merged.get(b)
                    if m is None:
                        merged[b] = m = UsageTree()
                    m.merge(t)
        if merged:
            with self._mu:
                self._trees = merged

    def _save_cache(self, info: DataUsageInfo) -> None:
        d = self._cache_disk()
        if d is None:
            return
        try:
            d.write_all(SYSTEM_VOL, USAGE_CACHE_FILE,
                        json.dumps(info.to_dict()).encode())
        except Exception:
            pass

    def _load_cache(self) -> DataUsageInfo | None:
        d = self._cache_disk()
        if d is None:
            return None
        try:
            return DataUsageInfo.from_dict(
                json.loads(d.read_all(SYSTEM_VOL, USAGE_CACHE_FILE))
            )
        except Exception:
            return None

    # -- queries --------------------------------------------------------------
    def data_usage_info(self) -> dict:
        with self._mu:
            return self.usage.to_dict()

    def usage_by_prefix(self, bucket: str, prefix: str = "") -> dict:
        """Exact usage at/under `bucket`/`prefix` from the merged
        hierarchical tree, with immediate children broken out (the
        reference's prefix-usage view over dataUsageCache)."""
        with self._mu:
            tree = self._trees.get(bucket)
            if tree is None:
                return {"prefix": prefix, "usage": {}, "children": {}}
            return {
                "prefix": prefix,
                "usage": tree.subtree(prefix),
                "children": tree.children_of(prefix),
            }
