"""Data scanner + data-usage accounting.

Equivalent of the reference's continuous scanner (runDataScanner,
cmd/data-scanner.go:97) and hierarchical usage cache
(cmd/data-usage-cache.go): walks every erasure set, aggregates per-bucket
usage (objects, versions, bytes, size histogram), triggers heal for
objects with missing shards, and evaluates lifecycle actions via a
pluggable callback.  The usage cache is persisted in the system volume so
admin/metrics queries don't rescan.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field

from minio_tpu.storage import errors
from minio_tpu.storage.local import SYSTEM_VOL

USAGE_CACHE_FILE = "data-usage.json"

# size histogram buckets, reference sizeHistogram (cmd/data-usage-cache.go)
SIZE_BUCKETS = [
    ("LESS_THAN_1024_B", 1024),
    ("BETWEEN_1024_B_AND_1_MB", 1024 * 1024),
    ("BETWEEN_1_MB_AND_10_MB", 10 * 1024 * 1024),
    ("BETWEEN_10_MB_AND_64_MB", 64 * 1024 * 1024),
    ("BETWEEN_64_MB_AND_128_MB", 128 * 1024 * 1024),
    ("BETWEEN_128_MB_AND_512_MB", 512 * 1024 * 1024),
    ("GREATER_THAN_512_MB", float("inf")),
]


def _histogram_bucket(size: int) -> str:
    for name, limit in SIZE_BUCKETS:
        if size < limit:
            return name
    return SIZE_BUCKETS[-1][0]


@dataclass
class BucketUsage:
    objects: int = 0
    versions: int = 0
    delete_markers: int = 0
    size: int = 0
    histogram: dict = field(default_factory=dict)

    def add(self, size: int, versions: int = 1, delete_markers: int = 0) -> None:
        self.objects += 1
        self.versions += versions
        self.delete_markers += delete_markers
        self.size += size
        b = _histogram_bucket(size)
        self.histogram[b] = self.histogram.get(b, 0) + 1

    def to_dict(self) -> dict:
        return {"objects": self.objects, "versions": self.versions,
                "deleteMarkers": self.delete_markers, "size": self.size,
                "histogram": self.histogram}

    @classmethod
    def from_dict(cls, d: dict) -> "BucketUsage":
        u = cls(objects=d.get("objects", 0), versions=d.get("versions", 0),
                delete_markers=d.get("deleteMarkers", 0),
                size=d.get("size", 0))
        u.histogram = dict(d.get("histogram", {}))
        return u


@dataclass
class DataUsageInfo:
    buckets: dict = field(default_factory=dict)   # bucket -> BucketUsage
    last_update: float = 0.0
    objects_scanned: int = 0
    heals_triggered: int = 0
    lifecycle_actions: int = 0
    lifecycle_errors: int = 0

    def total_size(self) -> int:
        return sum(u.size for u in self.buckets.values())

    def total_objects(self) -> int:
        return sum(u.objects for u in self.buckets.values())

    def to_dict(self) -> dict:
        return {
            "lastUpdate": self.last_update,
            "objectsTotalCount": self.total_objects(),
            "objectsTotalSize": self.total_size(),
            "objectsScanned": self.objects_scanned,
            "healsTriggered": self.heals_triggered,
            "lifecycleActions": self.lifecycle_actions,
            "lifecycleErrors": self.lifecycle_errors,
            "bucketsUsage": {b: u.to_dict() for b, u in self.buckets.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DataUsageInfo":
        info = cls(last_update=d.get("lastUpdate", 0.0),
                   objects_scanned=d.get("objectsScanned", 0),
                   heals_triggered=d.get("healsTriggered", 0),
                   lifecycle_actions=d.get("lifecycleActions", 0),
                   lifecycle_errors=d.get("lifecycleErrors", 0))
        info.buckets = {b: BucketUsage.from_dict(u)
                        for b, u in d.get("bucketsUsage", {}).items()}
        return info


class DataScanner:
    """Periodic scan of all sets: usage accounting + heal + lifecycle.

    lifecycle_fn(bucket, object_info) -> bool is called per scanned object
    version; returning True means the version was removed (expired /
    transitioned) and should not be counted.
    """

    def __init__(self, pools, interval: float = 60.0,
                 heal_queue=None, lifecycle_fn=None, autostart: bool = True,
                 tracker=None):
        self.pools = pools
        self.interval = interval
        self.heal_queue = heal_queue
        self.lifecycle_fn = lifecycle_fn
        self.tracker = tracker  # DataUpdateTracker; None -> always walk
        self.buckets_skipped = 0
        self.usage = DataUsageInfo()
        self.cycles = 0
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if autostart:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="data-scanner")
            self._thread.start()

    # -- loop ---------------------------------------------------------------
    def _run(self) -> None:
        # initial usage from the persisted cache so restarts serve stats
        cached = self._load_cache()
        if cached is not None:
            with self._mu:
                self.usage = cached
        while not self._stop.wait(self.interval):
            if getattr(self, "_paused", False):
                continue
            try:
                self.scan_cycle()
            except Exception:
                pass

    def pause(self) -> None:
        """Freeze cycles without tearing the thread down (peer
        signal-service stop-services, cmd/peer-rest-client.go:683)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def close(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    # -- one full cycle ------------------------------------------------------
    # in-progress uploads older than this are reclaimed even without a
    # lifecycle rule (reference cleanupStaleUploads default expiry,
    # cmd/erasure-sets.go:489)
    STALE_UPLOAD_EXPIRY = 24 * 3600.0

    def _cleanup_stale_uploads(self, es, info: DataUsageInfo) -> None:
        """ONE multipart walk per set per cycle; per-bucket lifecycle
        abort rules + the global stale expiry; orphaned upload dirs
        (unreadable/legacy metadata) are reclaimed once stale."""
        lf = self.lifecycle_fn
        try:
            uploads = es.enumerate_multipart_uploads()
        except Exception:
            return
        now = time.time()
        lc_cache: dict = {}
        for up in uploads:
            if not up.bucket:
                # orphan: no recoverable key — remove the raw dir when
                # old enough (initiated 0.0 = unreadable everywhere:
                # treat as stale)
                if now - up.initiated > self.STALE_UPLOAD_EXPIRY:
                    d0 = up.metadata.get("__dir", "")
                    for d in es.disks:
                        if d is None or not d.is_online() or not d0:
                            continue
                        try:
                            d.delete(SYSTEM_VOL, d0, recursive=True)
                        except Exception:
                            continue
                    info.lifecycle_actions += 1
                continue
            lc = lc_cache.get(up.bucket, False)
            if lc is False:
                lc = None
                if lf is not None and getattr(lf, "meta", None) is not None:
                    try:
                        lc = lf.meta.lifecycle(up.bucket)
                    except Exception:
                        lc = None
                lc_cache[up.bucket] = lc
            limit = self.STALE_UPLOAD_EXPIRY
            if lc is not None:
                days = lc.abort_multipart_days(up.object)
                if days > 0:
                    limit = min(limit, days * 86400.0)
            if up.initiated and now - up.initiated > limit:
                try:
                    es.abort_multipart_upload(up.bucket, up.object,
                                              up.upload_id)
                    info.lifecycle_actions += 1
                except Exception:
                    continue

    def scan_cycle(self) -> DataUsageInfo:
        info = DataUsageInfo(last_update=time.time())
        for pool in getattr(self.pools, "pools", [self.pools]):
            for es in pool.sets:
                self._scan_set(es, info)
        with self._mu:
            self.usage = info
        self.cycles += 1
        if self.tracker is not None:
            self.tracker.cycle()
        self._save_cache(info)
        return info

    def _scan_set(self, es, info: DataUsageInfo) -> None:
        from .heal import _set_buckets
        self._cleanup_stale_uploads(es, info)
        for bucket in _set_buckets(es):
            if self.tracker is not None \
                    and not self.tracker.bucket_dirty(bucket):
                # bloom filter proves no write touched the bucket since
                # the last cycle: reuse its usage, skip the drive walk
                # (reference dataUpdateTracker skip,
                # cmd/data-update-tracker.go)
                prev = self.usage.buckets.get(bucket)
                if prev is not None:
                    info.buckets[bucket] = prev
                    self.buckets_skipped += 1
                    continue
            usage = info.buckets.setdefault(bucket, BucketUsage())
            try:
                names = es.list_objects(bucket)
            except errors.StorageError:
                continue
            for name in names:
                info.objects_scanned += 1
                try:
                    fi, missing = es.object_health(bucket, name)
                except errors.StorageError:
                    # unreadable object: a heal attempt may still recover
                    # or purge a dangling entry
                    if self.heal_queue:
                        self.heal_queue(bucket, name, "")
                        info.heals_triggered += 1
                    continue
                if missing and self.heal_queue:
                    self.heal_queue(bucket, name, fi.version_id)
                    info.heals_triggered += 1
                # lifecycle evaluation
                if self.lifecycle_fn is not None:
                    try:
                        from minio_tpu.erasure.objects import ObjectInfo
                        oi = ObjectInfo.from_file_info(fi, bucket, name, True)
                        if self.lifecycle_fn(bucket, oi):
                            info.lifecycle_actions += 1
                            continue
                    except Exception:
                        # evaluation failures must not stop the scan, but a
                        # silently-broken ILM pipeline must be observable
                        info.lifecycle_errors += 1
                if fi.deleted:
                    usage.delete_markers += 1
                else:
                    usage.add(fi.size)
        return

    # -- persistence ----------------------------------------------------------
    def _cache_disk(self):
        for pool in getattr(self.pools, "pools", [self.pools]):
            for es in pool.sets:
                for d in es.disks:
                    if d is not None and d.is_online():
                        return d
        return None

    def _save_cache(self, info: DataUsageInfo) -> None:
        d = self._cache_disk()
        if d is None:
            return
        try:
            d.write_all(SYSTEM_VOL, USAGE_CACHE_FILE,
                        json.dumps(info.to_dict()).encode())
        except Exception:
            pass

    def _load_cache(self) -> DataUsageInfo | None:
        d = self._cache_disk()
        if d is None:
            return None
        try:
            return DataUsageInfo.from_dict(
                json.loads(d.read_all(SYSTEM_VOL, USAGE_CACHE_FILE))
            )
        except Exception:
            return None

    # -- queries --------------------------------------------------------------
    def data_usage_info(self) -> dict:
        with self._mu:
            return self.usage.to_dict()
