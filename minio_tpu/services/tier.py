"""Tiering: warm-tier backends, tier registry, transition, tier journal.

Reference: cmd/tier.go:386 (TierConfigMgr — named tier registry persisted
in the system volume), cmd/warm-backend-s3.go / warm-backend-minio.go
(remote warm backends), cmd/bucket-lifecycle.go (transitionObject:
upload to the tier, then replace local data with a metadata stub;
GET of a transitioned object streams through from the tier), and
cmd/tier-journal.go (deferred deletes of tiered data, retried until the
remote accepts them).

Backends here: `fs` (a local directory — single-host warm storage and
the test backend) and `s3` (any S3-compatible endpoint via the repo's
own SigV4 client).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from typing import Iterator

from minio_tpu.erasure.objects import (
    TRANSITION_COMPLETE, TRANSITION_KEY_KEY, TRANSITION_STATUS_KEY,
    TRANSITION_TIER_KEY,
)
from minio_tpu.storage import errors
from minio_tpu.utils.deadline import service_thread
from minio_tpu.storage.local import SYSTEM_VOL
from minio_tpu.utils.s3client import S3Client, S3ClientError

TIERS_PATH = "config/tiers.json"


class TierError(Exception):
    pass


# ------------------------------------------------------------- backends


class FSWarmBackend:
    """Warm tier on a local directory (also the test double)."""

    kind = "fs"

    def __init__(self, directory: str, prefix: str = ""):
        self.dir = directory
        self.prefix = prefix.strip("/")
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.join(self.dir, self.prefix, key) if self.prefix \
            else os.path.join(self.dir, key)
        ap = os.path.abspath(p)
        if not ap.startswith(os.path.abspath(self.dir) + os.sep):
            raise TierError(f"tier key escapes backend root: {key!r}")
        return ap

    def put(self, key: str, stream, length: int) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            for chunk in stream:
                f.write(chunk)
        os.replace(tmp, p)

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> Iterator[bytes]:
        p = self._path(key)
        try:
            f = open(p, "rb")
        except FileNotFoundError:
            raise TierError(f"tier object missing: {key}")
        try:
            f.seek(offset)
            remaining = length if length >= 0 else None
            while True:
                n = 1 << 20 if remaining is None else min(1 << 20, remaining)
                if n <= 0:
                    break
                chunk = f.read(n)
                if not chunk:
                    break
                if remaining is not None:
                    remaining -= len(chunk)
                yield chunk
        finally:
            f.close()

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class S3WarmBackend:
    """Warm tier on any S3-compatible endpoint."""

    kind = "s3"

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, prefix: str = "",
                 region: str = "us-east-1"):
        self.client = S3Client(endpoint, access_key, secret_key,
                               region=region)
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, stream, length: int) -> None:
        self.client.put_object(self.bucket, self._key(key), iter(stream),
                               length=length)

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> Iterator[bytes]:
        headers = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        try:
            return self.client.get_object_stream(
                self.bucket, self._key(key), headers=headers,
                ok=(200, 206))
        except S3ClientError as e:
            raise TierError(f"tier GET failed: {e}")

    def remove(self, key: str) -> None:
        try:
            self.client.delete_object(self.bucket, self._key(key))
        except S3ClientError as e:
            if e.status != 404:
                raise TierError(f"tier DELETE failed: {e}")


def _backend_from_cfg(cfg: dict):
    typ = cfg.get("type", "")
    if typ == "fs":
        return FSWarmBackend(cfg["directory"], cfg.get("prefix", ""))
    if typ == "s3":
        return S3WarmBackend(cfg["endpoint"], cfg["bucket"],
                             cfg.get("accessKey", ""),
                             cfg.get("secretKey", ""),
                             cfg.get("prefix", ""),
                             cfg.get("region", "us-east-1"))
    raise TierError(f"unknown tier type {typ!r}")


# -------------------------------------------------------------- journal


class TierJournal:
    """Deferred deletes of tiered objects, retried until the backend
    accepts them (reference cmd/tier-journal.go).  Reuses the notifier's
    file-per-entry persistent queue."""

    def __init__(self, directory: str, backend_for, retry: float = 5.0):
        from minio_tpu.events.targets import QueueStore

        self.store = QueueStore(directory)
        self.backend_for = backend_for  # tier name -> backend | None
        self._wake = threading.Event()
        self._closed = False
        self.retry = retry
        self.deleted = 0
        self._thread = service_thread(self._loop, name="tier-journal")

    def defer(self, tier: str, key: str) -> None:
        self.store.put({"tier": tier, "key": key})
        self._wake.set()

    def _loop(self) -> None:
        while not self._closed:
            keys = self.store.keys()
            if not keys:
                self._wake.wait(1.0)
                self._wake.clear()
                continue
            progressed = False
            for k in keys:
                if self._closed:
                    return
                entry = self.store.get(k)
                if entry is None:
                    self.store.delete(k)
                    continue
                backend = self.backend_for(entry.get("tier", ""))
                if backend is None:
                    # tier was removed: drop the entry
                    self.store.delete(k)
                    continue
                try:
                    backend.remove(entry["key"])
                    self.store.delete(k)
                    self.deleted += 1
                    progressed = True
                except Exception:
                    continue
            if not progressed:
                self._wake.wait(self.retry)
                self._wake.clear()

    def pending(self) -> int:
        return len(self.store)

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        self._thread.join(2)


# -------------------------------------------------------------- manager


class TierManager:
    """Named tier registry + transition/read-through/delete plumbing."""

    def __init__(self, api, journal_dir: str | None = None):
        self.api = api
        self._backends: dict[str, object] = {}
        self._mu = threading.Lock()
        self._io_lock = threading.Lock()  # orders _persist disk writes
        self._save_seq = 0
        self._persisted_seq = 0
        self.transitioned = 0
        self._load()
        if journal_dir is None:
            import tempfile

            journal_dir = os.path.join(tempfile.gettempdir(),
                                       "minio-tpu-tier-journal")
        self.journal = TierJournal(journal_dir, self.backend)
        # delete-hook wiring is gated on a non-empty tier registry: with
        # no tiers configured, deletes must not pay the extra metadata
        # read the hook requires
        self._wire_hooks()

    # -- registry ------------------------------------------------------------
    def _disks(self):
        pool = getattr(self.api, "pools", [self.api])[0]
        return [d for d in pool.all_disks
                if d is not None and d.is_online()]

    def _load(self) -> None:
        for d in self._disks():
            try:
                self._cfg = json.loads(d.read_all(SYSTEM_VOL, TIERS_PATH))
                return
            except (errors.StorageError, json.JSONDecodeError, ValueError):
                continue
        self._cfg = {}

    def _snapshot_locked(self) -> tuple[bytes, int]:
        """Serialize the tier table (caller holds self._mu); the seq
        orders out-of-lock persists so a stale snapshot cannot clobber
        a newer one."""
        self._save_seq += 1
        return json.dumps(self._cfg).encode(), self._save_seq

    def _persist(self, raw: bytes, seq: int) -> None:
        """Write a config snapshot WITHOUT holding self._mu, so tier
        lookups on the GET path never queue behind disk writes."""
        # lint: allow(blocking-under-lock): dedicated writer-ordering lock; the hot _mu is released before this
        with self._io_lock:
            if seq <= self._persisted_seq:
                return
            ok = 0
            for d in self._disks():
                try:
                    d.write_all(SYSTEM_VOL, TIERS_PATH, raw)
                    ok += 1
                except errors.StorageError:
                    continue
            if ok == 0:
                # advance the seq only on success: a failed persist must
                # not make an older pending snapshot (whose writes might
                # succeed) look already-superseded
                raise TierError("cannot persist tier config")
            self._persisted_seq = seq

    def _wire_hooks(self) -> None:
        hook = self._on_deleted if self._cfg else None
        for pool in getattr(self.api, "pools", [self.api]):
            for es in getattr(pool, "sets", []):
                es.tier_delete_hook = hook

    def _count(self, name: str, delta: int) -> None:
        """Persisted per-tier transitioned-object counter (the reference
        tracks tier usage to refuse removing an in-use tier)."""
        with self._mu:
            cfg = self._cfg.get(name)
            if cfg is None:
                return
            cfg["objects"] = max(0, int(cfg.get("objects", 0)) + delta)
            raw, seq = self._snapshot_locked()
        try:
            self._persist(raw, seq)
        except TierError:
            pass

    def add_tier(self, name: str, cfg: dict) -> None:
        name = name.strip()
        if not name:
            raise TierError("tier name required")
        cfg = dict(cfg)
        cfg.pop("objects", None)  # counter is server-managed
        _backend_from_cfg(cfg)  # validate eagerly
        with self._mu:
            prev = self._cfg.get(name)
            if prev is not None:
                cfg["objects"] = int(prev.get("objects", 0))
            self._cfg[name] = cfg
            self._backends.pop(name, None)
            raw, seq = self._snapshot_locked()
        self._persist(raw, seq)
        self._wire_hooks()

    def remove_tier(self, name: str, force: bool = False) -> None:
        with self._mu:
            if name not in self._cfg:
                raise TierError(f"no such tier {name!r}")
            in_use = int(self._cfg[name].get("objects", 0))
            if in_use > 0 and not force:
                raise TierError(
                    f"tier {name!r} still holds {in_use} transitioned "
                    "object(s); removing it would orphan them")
            del self._cfg[name]
            self._backends.pop(name, None)
            raw, seq = self._snapshot_locked()
        self._persist(raw, seq)
        self._wire_hooks()

    def list_tiers(self) -> list[dict]:
        with self._mu:
            out = []
            for name, cfg in sorted(self._cfg.items()):
                c = {k: v for k, v in cfg.items() if k != "secretKey"}
                out.append({"name": name, **c})
            return out

    def backend(self, name: str):
        with self._mu:
            b = self._backends.get(name)
            if b is not None:
                return b
            cfg = self._cfg.get(name)
            if cfg is None:
                return None
            b = _backend_from_cfg(cfg)
            self._backends[name] = b
            return b

    # -- transition ----------------------------------------------------------
    def transition(self, bucket: str, oi, tier: str) -> bool:
        """lifecycle transition_fn: move the version's stored bytes to
        the tier and leave a stub (reference transitionObject)."""
        backend = self.backend(tier)
        if backend is None:
            return False
        if (oi.metadata or {}).get(TRANSITION_STATUS_KEY) == \
                TRANSITION_COMPLETE:
            return False  # already tiered
        vid = oi.version_id or "null"
        key = f"{bucket}/{oi.name}/{vid}/{uuid.uuid4().hex}"
        oi2, stream = self.api.get_object(bucket, oi.name,
                                          version_id=oi.version_id)
        try:
            backend.put(key, iter(stream), oi2.size)
        finally:
            if hasattr(stream, "close"):
                stream.close()
        try:
            self.api.transition_version(
                bucket, oi.name, oi.version_id,
                {
                    TRANSITION_STATUS_KEY: TRANSITION_COMPLETE,
                    TRANSITION_TIER_KEY: tier,
                    TRANSITION_KEY_KEY: key,
                },
                expected_mod_time=oi2.mod_time)
        except errors.ErasureWriteQuorum:
            # PARTIAL stub write: some drives already freed their shards
            # and reference the tier key — the tier copy may now be the
            # only full copy, never reclaim it here (heal converges the
            # metadata; the key is reclaimed when the version is deleted)
            return False
        except Exception:
            # rejected before any drive freed data (version changed, not
            # found): the tier copy is a true orphan — reclaim it
            self.journal.defer(tier, key)
            return False
        self.transitioned += 1
        self._count(tier, +1)
        return True

    # -- read-through --------------------------------------------------------
    @staticmethod
    def is_transitioned(metadata: dict | None) -> bool:
        return bool(metadata) and \
            metadata.get(TRANSITION_STATUS_KEY) == TRANSITION_COMPLETE

    def read(self, metadata: dict, offset: int = 0,
             length: int = -1) -> Iterator[bytes]:
        tier = metadata.get(TRANSITION_TIER_KEY, "")
        key = metadata.get(TRANSITION_KEY_KEY, "")
        backend = self.backend(tier)
        if backend is None:
            raise TierError(f"tier {tier!r} is not configured")
        return backend.get(key, offset, length)

    # -- delete --------------------------------------------------------------
    def _on_deleted(self, metadata: dict) -> None:
        tier = metadata.get(TRANSITION_TIER_KEY, "")
        key = metadata.get(TRANSITION_KEY_KEY, "")
        if tier and key:
            self.journal.defer(tier, key)
            self._count(tier, -1)

    def close(self) -> None:
        self.journal.close()
