"""Pool decommission: drain one server pool's objects into the rest.

Reference: cmd/erasure-server-pool-decom.go — `mc admin decommission
start myminio/ http://pool1/...` walks every bucket of the draining
pool, moves each object version into the remaining pools, and records
resumable progress; placement stops selecting the pool the moment the
drain starts.

Design here: the drain job walks the source pool's entry stream
(name + all versions), re-puts each live version into the surviving
pools with its version id AND mod time pinned (PutObjectOptions
version_id/mod_time), re-creates delete markers, then deletes the
source copy.  State persists on the source pool's first online drive
(`decommission.json`) so a restart resumes (bucket granularity) and a
completed pool stays excluded from placement.
"""

from __future__ import annotations

import io
import json
import threading
import time

from minio_tpu.storage import errors
from minio_tpu.storage.local import SYSTEM_VOL

DECOM_FILE = "decommission.json"

_STATES = ("none", "draining", "complete", "failed", "canceled")


def _state_disk(pool):
    for d in pool.all_disks:
        try:
            if d is not None and d.is_online():
                return d
        except Exception:
            continue
    return None


def load_state(pool) -> dict:
    d = _state_disk(pool)
    if d is None:
        return {"state": "none"}
    try:
        return json.loads(d.read_all(SYSTEM_VOL, DECOM_FILE))
    except Exception:
        return {"state": "none"}


def save_state(pool, state: dict) -> None:
    d = _state_disk(pool)
    if d is not None:
        try:
            d.write_all(SYSTEM_VOL, DECOM_FILE,
                        json.dumps(state).encode())
        except Exception:
            pass


class PoolDecommission:
    """One drain job over `pools` (ErasureServerPools), emptying
    pools.pools[idx] into the others."""

    def __init__(self, pools, idx: int):
        if not 0 <= idx < len(pools.pools):
            raise errors.InvalidArgument(f"no pool {idx}")
        if len(pools.pools) < 2:
            raise errors.InvalidArgument(
                "cannot decommission the only pool")
        self.pools = pools
        self.idx = idx
        self.src = pools.pools[idx]
        self.state = load_state(self.src)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- control ------------------------------------------------------------
    def start(self) -> None:
        if self.state.get("state") == "draining":
            raise errors.InvalidArgument("decommission already running")
        if self.state.get("state") == "complete":
            raise errors.InvalidArgument("pool already decommissioned")
        self.state = {
            "state": "draining", "started": time.time(),
            "moved_objects": 0, "moved_bytes": 0, "failed_objects": 0,
            "done_buckets": [],
        }
        save_state(self.src, self.state)
        self.pools.mark_draining(self.idx, True)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"decom-pool-{self.idx}")
        self._thread.start()

    def cancel(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.state["state"] = "canceled"
        save_state(self.src, self.state)
        self.pools.mark_draining(self.idx, False)

    def wait(self, timeout: float = 600.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- drain --------------------------------------------------------------
    def _run(self) -> None:
        try:
            for vol in self.src.list_buckets():
                bucket = vol.name
                if self._stop.is_set():
                    return
                if bucket in self.state["done_buckets"]:
                    continue
                self._drain_bucket(bucket)
                self.state["done_buckets"].append(bucket)
                save_state(self.src, self.state)
            self.state["state"] = "complete"
            self.state["finished"] = time.time()
        except Exception as e:
            self.state["state"] = "failed"
            self.state["error"] = str(e)
        save_state(self.src, self.state)

    def _drain_bucket(self, bucket: str) -> None:
        for entry in self.src.list_entries(bucket):
            if self._stop.is_set():
                return
            name = entry.name
            # oldest-first so xl.meta mod-time ordering (and is_latest)
            # lands identically in the target pool
            for oi in reversed(entry.versions):
                try:
                    self._move_version(bucket, name, oi)
                    self.state["moved_objects"] += 1
                    self.state["moved_bytes"] += max(oi.size, 0)
                except Exception:
                    self.state["failed_objects"] += 1

    def _move_version(self, bucket: str, name: str, oi) -> None:
        from minio_tpu.erasure.objects import PutObjectOptions

        target = self._target_pool(name, max(oi.size, 0))
        if oi.delete_marker:
            # replay the marker with its id + mod time pinned, then drop
            # the source's copy
            target.put_delete_marker(bucket, name, oi.version_id or "",
                                     oi.mod_time)
            self.src.delete_object(bucket, name,
                                   version_id=oi.version_id or "null")
            return
        _, stream = self.src.get_object(
            bucket, name, version_id=oi.version_id)
        meta = {k: v for k, v in oi.metadata.items()
                if k not in ("etag", "content-type")}
        opts = PutObjectOptions(
            user_metadata=meta,
            content_type=oi.content_type,
            versioned=bool(oi.version_id),
            version_id=oi.version_id,
            mod_time=oi.mod_time,
        )
        reader = _IterReader(stream)
        target.put_object(bucket, name, reader, oi.size, opts)
        self.src.delete_object(bucket, name,
                               version_id=oi.version_id or "null")

    def _target_pool(self, obj: str, size: int):
        avail = self.pools._pool_available(obj, size)
        best, best_a = None, -1
        for i, (p, a) in enumerate(zip(self.pools.pools, avail)):
            if i == self.idx:
                continue
            if a > best_a:
                best, best_a = p, a
        if best is None or best_a <= 0:
            raise errors.DiskFull("no target pool has space")
        return best


class _IterReader(io.RawIOBase):
    """File-like over the get_object chunk iterator."""

    def __init__(self, chunks):
        self._it = iter(chunks)
        self._buf = b""

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            out = self._buf + b"".join(self._it)
            self._buf = b""
            return out
        while len(self._buf) < n:
            try:
                self._buf += next(self._it)
            except StopIteration:
                break
        out, self._buf = self._buf[:n], self._buf[n:]
        return out
