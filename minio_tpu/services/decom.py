"""Pool decommission: drain one server pool's objects into the rest.

Reference: cmd/erasure-server-pool-decom.go — `mc admin decommission
start myminio/ http://pool1/...` walks every bucket of the draining
pool, moves each object version into the remaining pools, and records
resumable progress; placement stops selecting the pool the moment the
drain starts.

Design here: the drain job walks the source pool's entry stream
(name + all versions), re-puts each live version into the surviving
pools with its version id AND mod time pinned (PutObjectOptions
version_id/mod_time), re-creates delete markers, then deletes the
source copy.  State persists to a WRITE QUORUM of the source pool's
drives (`decommission.json`, seq-versioned) so losing any minority of
drives — including whichever wrote first — cannot lose drain progress;
a restart resumes (bucket granularity) and a completed pool stays
excluded from placement.  Saves that miss quorum mark the job degraded
in admin status instead of failing silently (reference persists pool
meta under .minio.sys with quorum semantics,
cmd/erasure-server-pool-decom.go poolMeta.save).
"""

from __future__ import annotations

import io
import json
import threading
import time

from minio_tpu.storage import errors
from minio_tpu.utils.deadline import service_thread
from minio_tpu.storage.local import SYSTEM_VOL

DECOM_FILE = "decommission.json"
REBAL_FILE = "rebalance.json"

_STATES = ("none", "draining", "complete", "failed", "canceled")


def load_state(pool, filename: str = DECOM_FILE) -> dict:
    """Read every drive's copy and return the newest (highest seq) —
    any surviving member of the last write quorum is enough to resume."""
    best, best_seq = {"state": "none"}, -1
    for d in pool.all_disks:
        try:
            if d is None or not d.is_online():
                continue
            st = json.loads(d.read_all(SYSTEM_VOL, filename))
            seq = int(st.get("seq", 0))
        except Exception:
            continue  # unreadable/corrupt copy: ignore, others decide
        if seq > best_seq:
            best, best_seq = st, seq
    return best


def save_state(pool, state: dict, filename: str = DECOM_FILE) -> bool:
    """Persist to ALL online drives of the pool; True iff a write
    quorum (n//2+1 of the pool's drive slots) accepted it.  The seq
    counter makes load_state pick the newest copy after partial
    failures."""
    state["seq"] = int(state.get("seq", 0)) + 1
    payload = json.dumps(state).encode()
    disks = [d for d in pool.all_disks if d is not None]
    quorum = len(disks) // 2 + 1
    ok = 0
    for d in disks:
        try:
            if not d.is_online():
                continue
            d.write_all(SYSTEM_VOL, filename, payload)
            ok += 1
        except Exception:
            continue
    return ok >= quorum


class PoolDecommission:
    """One drain job over `pools` (ErasureServerPools), emptying
    pools.pools[idx] into the others."""

    def __init__(self, pools, idx: int):
        if not 0 <= idx < len(pools.pools):
            raise errors.InvalidArgument(f"no pool {idx}")
        if len(pools.pools) < 2:
            raise errors.InvalidArgument(
                "cannot decommission the only pool")
        self.pools = pools
        self.idx = idx
        self.src = pools.pools[idx]
        self.state = load_state(self.src)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _save(self) -> None:
        """Quorum-persist; a save that misses quorum marks the job
        degraded in status (visible via the pools admin API) instead of
        silently continuing with unpersisted progress."""
        self.state["degraded"] = False
        if not save_state(self.src, self.state):
            self.state["degraded"] = True

    # -- control ------------------------------------------------------------
    def start(self) -> None:
        if self.state.get("state") == "draining" \
                and self._thread is not None and self._thread.is_alive():
            raise errors.InvalidArgument("decommission already running")
        if self.state.get("state") == "complete":
            raise errors.InvalidArgument("pool already decommissioned")
        # a persisted 'draining' with no live thread is a crashed drain:
        # restarting resumes from the completed-bucket list, like
        # failed/canceled restarts
        resume_from = self.state.get("done_buckets", []) \
            if self.state.get("state") in ("draining", "failed",
                                           "canceled") else []
        self.state = {
            "state": "draining", "started": time.time(),
            "moved_objects": 0, "moved_bytes": 0, "failed_objects": 0,
            "done_buckets": list(resume_from),
            "seq": int(self.state.get("seq", 0)),
        }
        self._save()
        self.pools.mark_draining(self.idx, True)
        self._thread = service_thread(
            self._run, name=f"decom-pool-{self.idx}")

    def cancel(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.state["state"] = "canceled"
        self._save()
        self.pools.mark_draining(self.idx, False)

    def wait(self, timeout: float = 600.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- drain --------------------------------------------------------------
    def _run(self) -> None:
        try:
            for vol in self.src.list_buckets():
                bucket = vol.name
                if self._stop.is_set():
                    return
                if bucket in self.state["done_buckets"]:
                    continue
                self._drain_bucket(bucket)
                self.state["done_buckets"].append(bucket)
                self._save()
            self.state["state"] = "complete"
            self.state["finished"] = time.time()
        except Exception as e:
            self.state["state"] = "failed"
            self.state["error"] = str(e)
        self._save()

    def _drain_bucket(self, bucket: str) -> None:
        for entry in self.src.list_entries(bucket):
            if self._stop.is_set():
                return
            name = entry.name
            # oldest-first so xl.meta mod-time ordering (and is_latest)
            # lands identically in the target pool
            for oi in reversed(entry.versions):
                try:
                    self._move_version(bucket, name, oi)
                    self.state["moved_objects"] += 1
                    self.state["moved_bytes"] += max(oi.size, 0)
                except Exception:
                    self.state["failed_objects"] += 1

    def _move_version(self, bucket: str, name: str, oi) -> None:
        target = self._target_pool(name, max(oi.size, 0))
        move_version(self.src, target, bucket, name, oi)

    def _target_pool(self, obj: str, size: int):
        avail = self.pools._pool_available(obj, size)
        best, best_a = None, -1
        for i, (p, a) in enumerate(zip(self.pools.pools, avail)):
            if i == self.idx:
                continue
            if a > best_a:
                best, best_a = p, a
        if best is None or best_a <= 0:
            raise errors.DiskFull("no target pool has space")
        return best


def move_version(src, target, bucket: str, name: str, oi) -> None:
    """Move one object version between pools with its version id and
    mod time pinned — shared by decommission and rebalance."""
    from minio_tpu.erasure.objects import PutObjectOptions

    if oi.delete_marker:
        # replay the marker with its id + mod time pinned, then drop
        # the source's copy
        target.put_delete_marker(bucket, name, oi.version_id or "",
                                 oi.mod_time)
        src.delete_object(bucket, name,
                          version_id=oi.version_id or "null")
        return
    _, stream = src.get_object(bucket, name, version_id=oi.version_id)
    meta = {k: v for k, v in oi.metadata.items()
            if k not in ("etag", "content-type")}
    opts = PutObjectOptions(
        user_metadata=meta,
        content_type=oi.content_type,
        versioned=bool(oi.version_id),
        version_id=oi.version_id,
        mod_time=oi.mod_time,
        # carry the ETag verbatim: a multipart (md5-N) or SSE/compressed
        # ETag recomputed from the drained stream would differ and break
        # If-Match / client caches (ADVICE r4 medium)
        etag=oi.etag or oi.metadata.get("etag", ""),
    )
    target.put_object(bucket, name, _IterReader(stream), oi.size, opts)
    src.delete_object(bucket, name, version_id=oi.version_id or "null")


class PoolRebalance:
    """Spread existing objects so pool fill fractions converge — run
    after expanding a deployment with a new (empty) pool (reference
    cmd/erasure-server-pool-rebalance.go; `mc admin rebalance start`).

    Pools whose used fraction exceeds the cluster average by more than
    `tolerance` donate objects to the emptiest pool until they fall
    within it."""

    def __init__(self, pools, tolerance: float = 0.02):
        if len(pools.pools) < 2:
            raise errors.InvalidArgument("rebalance needs multiple pools")
        self.pools = pools
        self.tolerance = tolerance
        # rebalance meta lives on the FIRST pool's drives, quorum-written
        # like decom state (reference rebalanceMeta under .minio.sys)
        self.state = load_state(pools.pools[0], REBAL_FILE)
        if self.state.get("state") == "running":
            # persisted 'running' with no thread = a previous process
            # died mid-rebalance; surface that instead of lying
            self.state["state"] = "interrupted"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _save(self) -> None:
        self.state["degraded"] = False
        if not save_state(self.pools.pools[0], self.state, REBAL_FILE):
            self.state["degraded"] = True

    # -- capacity math ------------------------------------------------------
    def _capacity(self, fresh: bool = False) -> list[tuple[int, int]]:
        """[(total, used)] per pool; fresh=True re-measures past any
        usage caches (the convergence loop must see its own moves)."""
        out = []
        for p in self.pools.pools:
            total = used = 0
            for d in p.all_disks:
                try:
                    if d is None or not d.is_online():
                        continue
                    if fresh:
                        inner = getattr(d, "_inner", d)
                        inv = getattr(inner, "invalidate_usage_cache", None)
                        if inv is not None:
                            inv()
                    di = d.disk_info()
                    total += di.total
                    used += di.used
                except Exception:
                    continue
            out.append((total, used))
        return out

    def _fractions(self) -> list[float]:
        return [u / t if t else 0.0 for t, u in self._capacity()]

    def status(self) -> dict:
        return {**self.state, "fill": [round(f, 4)
                                       for f in self._fractions()]}

    # -- control ------------------------------------------------------------
    def start(self) -> None:
        if self.state.get("state") == "running":
            raise errors.InvalidArgument("rebalance already running")
        self.state = {"state": "running", "started": time.time(),
                      "moved_objects": 0, "moved_bytes": 0,
                      "failed_objects": 0,
                      "seq": int(self.state.get("seq", 0))}
        self._save()
        self._stop.clear()
        self._thread = service_thread(self._run, name="pool-rebalance")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.state.get("state") == "running":
            self.state["state"] = "stopped"
        self._save()

    def wait(self, timeout: float = 600.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- loop ---------------------------------------------------------------
    def _run(self) -> None:
        try:
            for _ in range(4):  # bounded convergence rounds
                if self._stop.is_set():
                    break
                caps = self._capacity(fresh=True)
                fracs = [u / t if t else 0.0 for t, u in caps]
                avg = sum(fracs) / len(fracs)
                donors = [i for i, f in enumerate(fracs)
                          if f > avg + self.tolerance
                          and i not in self.pools._draining]
                if not donors:
                    break
                moved_any = False
                for i in donors:
                    # byte budget computed up front: the du cache lags
                    # moves, so steering by live fractions over-drains
                    over = int((fracs[i] - avg) * caps[i][0])
                    if self._donate(i, over, fracs):
                        moved_any = True
                self._save()
                if not moved_any:
                    break
            self.state["state"] = "complete"
            self.state["finished"] = time.time()
        except Exception as e:
            self.state["state"] = "failed"
            self.state["error"] = str(e)
        self._save()

    def _donate(self, idx: int, budget: int, fracs: list[float]) -> bool:
        """Move ~`budget` logical bytes out of pool `idx` into the
        emptiest other pools; True if anything moved."""
        src = self.pools.pools[idx]
        caps = self._capacity()
        est = list(fracs)  # locally-updated estimates
        donated = 0
        moved = 0
        # erasure overhead: logical bytes land ~N/K larger on disk
        overhead = 2.0
        for vol in src.list_buckets():
            bucket = vol.name
            for entry in src.list_entries(bucket):
                if self._stop.is_set() or donated >= budget:
                    return moved > 0
                tgt_i = min(
                    (i for i in range(len(est)) if i != idx
                     and i not in self.pools._draining),
                    key=lambda i: est[i], default=None)
                if tgt_i is None:
                    return moved > 0
                target = self.pools.pools[tgt_i]
                try:
                    obj_bytes = 0
                    for oi in reversed(entry.versions):
                        move_version(src, target, bucket, entry.name, oi)
                        self.state["moved_objects"] += 1
                        self.state["moved_bytes"] += max(oi.size, 0)
                        obj_bytes += max(oi.size, 0)
                    moved += 1
                    donated += int(obj_bytes * overhead)
                    if caps[tgt_i][0]:
                        est[tgt_i] += obj_bytes * overhead / caps[tgt_i][0]
                except Exception:
                    self.state["failed_objects"] += 1
        return moved > 0


class _IterReader(io.RawIOBase):
    """File-like over the get_object chunk iterator."""

    def __init__(self, chunks):
        self._it = iter(chunks)
        self._buf = b""

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            out = self._buf + b"".join(self._it)
            self._buf = b""
            return out
        while len(self._buf) < n:
            try:
                self._buf += next(self._it)
            except StopIteration:
                break
        out, self._buf = self._buf[:n], self._buf[n:]
        return out
