"""Pool decommission: drain one server pool's objects into the rest.

Reference: cmd/erasure-server-pool-decom.go — `mc admin decommission
start myminio/ http://pool1/...` walks every bucket of the draining
pool, moves each object version into the remaining pools, and records
resumable progress; placement stops selecting the pool the moment the
drain starts.

Design here (ISSUE 14 hardening, protocol modeled in
analysis/concurrency/models/topology.py): the drain job walks the
source pool's entry stream (name + all versions) and moves each live
version with the **write-fence invariant** — a version is deleted from
the source pool only after the destination copy is quorum-committed
(put_object met write quorum) AND the source set's ``ns_updated`` choke
point has fired (hot tier + metacache + change tracker invalidation),
so a cached route can never point at a deleted copy.  A version the
destination already holds same-or-newer (an overwrite PUT that landed
on a live pool mid-drain) is never clobbered: the stale source copy is
simply dropped (the model's copy-clobbers-newer mutation).

Progress checkpoints at **object granularity**: ``decommission.json``
(seq-versioned, quorum-persisted on the source pool's drives) carries
the completed-bucket list AND an in-bucket cursor (last fully-moved
object name), saved every ``MINIO_TPU_DECOM_CHECKPOINT_EVERY`` objects
— a kill mid-bucket resumes after the last checkpointed object instead
of replaying the bucket.  The cursor is advanced only AFTER the
source-side delete landed (the model's checkpoint-ahead mutation is the
bug class this ordering kills).  Saves that miss quorum mark the job
degraded in admin status instead of failing silently (reference
persists pool meta under .minio.sys with quorum semantics,
cmd/erasure-server-pool-decom.go poolMeta.save).

Per-object moves run under a deadline budget
(``MINIO_TPU_DECOM_OBJ_TIMEOUT_S``) and are retried MRF-style with
permanent/retryable classification (a version deleted mid-drain by a
client is "gone", not a failure); drain traffic defers to foreground
load through the brownout throttle like every other background plane.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time

from minio_tpu.storage import errors
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import Budget, scope, service_thread
from minio_tpu.storage.local import SYSTEM_VOL

DECOM_FILE = "decommission.json"
REBAL_FILE = "rebalance.json"

_STATES = ("none", "draining", "complete", "failed", "canceled")

#: topology-plane counters rendered as minio_topology_* gauges
#: (server/metrics.py); module-level so admin-created jobs and
#: process-lifetime totals agree
stats = {
    "drained_objects": 0,
    "drained_bytes": 0,
    "retries": 0,
    "failed_retryable": 0,
    "failed_permanent": 0,
    "skipped_stale": 0,      # source copies dropped (dest same-or-newer)
    "throttle_waits": 0,
}
_stats_mu = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _stats_mu:
        stats[key] += n


class _DrainKilled(BaseException):
    """Test-only crash injection: the drain thread dies WITHOUT saving
    state — the closest a thread can come to SIGKILL mid-flight."""


class MoveFailed(Exception):
    def __init__(self, msg: str, permanent: bool):
        super().__init__(msg)
        self.permanent = permanent


#: errors that mean the version is GONE (deleted mid-drain by a
#: client) — nothing left to move, not a failure
_GONE = (errors.ObjectNotFound, errors.VersionNotFound,
         errors.BucketNotFound, errors.FileNotFound,
         errors.FileVersionNotFound)


def _classify(exc: Exception) -> str:
    if isinstance(exc, _GONE):
        return "gone"
    if isinstance(exc, (errors.InvalidArgument,)):
        return "permanent"
    return "retryable"


def load_state(pool, filename: str = DECOM_FILE) -> dict:
    """Read every drive's copy and return the newest (highest seq) —
    any surviving member of the last write quorum is enough to resume."""
    best, best_seq = {"state": "none"}, -1
    for d in pool.all_disks:
        try:
            if d is None or not d.is_online():
                continue
            st = json.loads(d.read_all(SYSTEM_VOL, filename))
            seq = int(st.get("seq", 0))
        except Exception:
            continue  # unreadable/corrupt copy: ignore, others decide
        if seq > best_seq:
            best, best_seq = st, seq
    return best


def save_state(pool, state: dict, filename: str = DECOM_FILE) -> bool:
    """Persist to ALL online drives of the pool; True iff a write
    quorum (n//2+1 of the pool's drive slots) accepted it.  The seq
    counter makes load_state pick the newest copy after partial
    failures."""
    state["seq"] = int(state.get("seq", 0)) + 1
    payload = json.dumps(state).encode()
    disks = [d for d in pool.all_disks if d is not None]
    quorum = len(disks) // 2 + 1
    ok = 0
    for d in disks:
        try:
            if not d.is_online():
                continue
            d.write_all(SYSTEM_VOL, filename, payload)
            ok += 1
        except Exception:
            continue
    return ok >= quorum


class PoolDecommission:
    """One drain job over `pools` (ErasureServerPools), emptying
    pools.pools[idx] into the others."""

    def __init__(self, pools, idx: int):
        if not 0 <= idx < len(pools.pools):
            raise errors.InvalidArgument(f"no pool {idx}")
        if len(pools.pools) < 2:
            raise errors.InvalidArgument(
                "cannot decommission the only pool")
        self.pools = pools
        self.idx = idx
        self.src = pools.pools[idx]
        self.state = load_state(self.src)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # drain traffic defers to foreground load (wired to
        # services.brownout.background_allowed by the admin plane)
        self.throttle = None
        self.checkpoint_every = max(1, int(os.environ.get(
            "MINIO_TPU_DECOM_CHECKPOINT_EVERY", "32")))
        self.retries = max(0, int(os.environ.get(
            "MINIO_TPU_DECOM_RETRIES", "3")))
        self.obj_timeout = float(os.environ.get(
            "MINIO_TPU_DECOM_OBJ_TIMEOUT_S", "120"))
        # test-only: fn(moved_objects) -> True kills the drain thread
        # without a final save (crash injection for the chaos drill)
        self._crash_hook = None
        self._since_ckpt = 0

    def _save(self) -> None:
        """Quorum-persist; a save that misses quorum marks the job
        degraded in status (visible via the pools admin API) instead of
        silently continuing with unpersisted progress."""
        self.state["degraded"] = False
        if not save_state(self.src, self.state):
            self.state["degraded"] = True

    # -- control ------------------------------------------------------------
    def start(self) -> None:
        if self.state.get("state") == "draining" \
                and self._thread is not None and self._thread.is_alive():
            raise errors.InvalidArgument("decommission already running")
        if self.state.get("state") == "complete":
            raise errors.InvalidArgument("pool already decommissioned")
        # a persisted 'draining' with no live thread is a crashed drain:
        # restarting resumes from the checkpointed cursor, like
        # failed/canceled restarts
        resume = self.state.get("state") in ("draining", "failed",
                                             "canceled")
        resume_from = self.state.get("done_buckets", []) if resume else []
        cursor = self.state.get("cursor") if resume else None
        self.state = {
            "state": "draining", "started": time.time(),
            "moved_objects": 0, "moved_bytes": 0, "failed_objects": 0,
            "retried_objects": 0, "skipped_stale": 0, "throttle_waits": 0,
            "done_buckets": list(resume_from),
            "cursor": dict(cursor) if cursor else None,
            "seq": int(self.state.get("seq", 0)),
        }
        # placement suspension BEFORE the first move (and before the
        # durable save, so a crash between the two leaves the pool
        # suspended-at-boot via the persisted 'draining' state): a PUT
        # racing the drain start must never land behind the cursor
        # (the model's suspend-after-drain-starts mutation)
        self.pools.mark_draining(self.idx, True)
        self._save()
        self._thread = service_thread(
            self._run, name=f"decom-pool-{self.idx}")

    def cancel(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # reconcile BEFORE the pool rejoins read order: overwrites that
        # landed on live pools while this one was suspended left STALE
        # copies here, and back in (index-ordered) read order a stale
        # null version would shadow the newer live-pool copy on every
        # read — a persistent read-your-writes violation.  Drop every
        # local copy another pool already holds same-or-newer (this
        # also clears duplicate version-ids from moves killed between
        # dest-commit and source-delete).
        try:
            self._reconcile_stale()
        except Exception:
            pass  # best effort: a later drain/heal converges the rest
        self.state["state"] = "canceled"
        self._save()
        # a canceled pool returns to placement
        self.pools.mark_draining(self.idx, False)

    def _reconcile_stale(self) -> None:
        others = [p for i, p in enumerate(self.pools.pools)
                  if i != self.idx]
        for vol in self.src.list_buckets():
            bucket = vol.name
            try:
                entries = list(self.src.list_entries(bucket))
            except errors.StorageError:
                continue
            for entry in entries:
                for oi in entry.versions:
                    if any(_dest_has_same_or_newer(other, bucket,
                                                   entry.name, oi)
                           for other in others):
                        _bump("skipped_stale")
                        _fence(self.src, bucket, entry.name)
                        try:
                            self.src.delete_object(
                                bucket, entry.name,
                                version_id=oi.version_id or "null")
                        except errors.StorageError:
                            continue

    def wait(self, timeout: float = 600.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- drain --------------------------------------------------------------
    def _throttle_wait(self) -> None:
        """Brownout deferral between objects: foreground load owns the
        drives; the drain resumes when the controller releases."""
        if self.throttle is None or self.throttle():
            return
        self.state["throttle_waits"] += 1
        _bump("throttle_waits")
        while not self._stop.is_set() and not self.throttle():
            time.sleep(0.05)

    def _residual_buckets(self) -> list[str]:
        """Buckets of the source pool still holding ANY object record
        (versions or delete markers)."""
        out = []
        for vol in self.src.list_buckets():
            try:
                if next(iter(self.src.list_entries(vol.name)),
                        None) is not None:
                    out.append(vol.name)
            except errors.StorageError:
                continue
        return out

    def _run(self) -> None:
        root = tracing.start("topology.decom", pool=self.idx)
        token = tracing.install(root) if root is not None else None
        t0 = time.monotonic()
        status = 200
        try:
            try:
                # The walk + a bounded number of VERIFICATION sweeps.
                # Placement suspension is marked before the first move,
                # but a racing PUT can resolve its pool routing BEFORE
                # the suspension became visible and land its write in
                # this pool behind the cursor (routing-decision vs
                # write-landing TOCTOU — the model's client_put is
                # atomic, the real plane is not).  Re-listing after the
                # walk catches such stragglers; by the second sweep the
                # suspension has long been visible, so this converges.
                for sweep in range(3):
                    for vol in self.src.list_buckets():
                        bucket = vol.name
                        if self._stop.is_set():
                            return
                        if bucket in self.state["done_buckets"]:
                            continue
                        with tracing.span("decom.bucket", bucket=bucket,
                                          sweep=sweep):
                            self._drain_bucket(bucket)
                        self.state["done_buckets"].append(bucket)
                        self.state["cursor"] = None
                        self._save()
                    if self._stop.is_set():
                        return
                    residual = self._residual_buckets()
                    if not residual:
                        break
                    tracing.event("decom.verify.residual",
                                  buckets=len(residual), sweep=sweep)
                    self.state["done_buckets"] = [
                        b for b in self.state["done_buckets"]
                        if b not in residual]
                    self.state["cursor"] = None
                    self._save()
                else:
                    residual = self._residual_buckets()
                    if residual:
                        self.state["failed_objects"] += 1
                        self.state.setdefault(
                            "error", "source pool still non-empty "
                            "after verification sweeps")
                if self.state["failed_objects"] > 0:
                    # objects remain in the source pool: the drain is NOT
                    # complete — a restart resumes and retries them
                    self.state["state"] = "failed"
                    self.state["error"] = (
                        f"{self.state['failed_objects']} objects failed "
                        "to move; restart the decommission to retry")
                    status = 500
                else:
                    self.state["state"] = "complete"
                    self.state["finished"] = time.time()
            except _DrainKilled:
                status = 500
                return  # crash injection: NO save (simulated SIGKILL)
            except Exception as e:
                self.state["state"] = "failed"
                self.state["error"] = str(e)
                status = 500
            self._save()
        finally:
            if root is not None:
                root.tag(moved=self.state.get("moved_objects", 0),
                         failed=self.state.get("failed_objects", 0))
                tracing.reset(token)
                tracing.finish(root, status=status, error=status >= 500,
                               duration=time.monotonic() - t0)

    def _drain_bucket(self, bucket: str) -> None:
        cur = self.state.get("cursor") or {}
        start_after = cur.get("obj", "") if cur.get("bucket") == bucket \
            else ""
        for entry in self.src.list_entries(bucket):
            if self._stop.is_set():
                self._save()  # checkpoint what we finished
                return
            name = entry.name
            if start_after and name <= start_after:
                continue  # already moved before the crash/restart
            self._throttle_wait()
            if self._crash_hook is not None \
                    and self._crash_hook(self.state["moved_objects"]):
                raise _DrainKilled()
            # oldest-first so xl.meta mod-time ordering (and is_latest)
            # lands identically in the target pool
            obj_failed = False
            for oi in reversed(entry.versions):
                try:
                    self._move_version(bucket, name, oi)
                    self.state["moved_objects"] += 1
                    self.state["moved_bytes"] += max(oi.size, 0)
                    _bump("drained_objects")
                    _bump("drained_bytes", max(oi.size, 0))
                except MoveFailed as mf:
                    obj_failed = True
                    self.state["failed_objects"] += 1
                    _bump("failed_permanent" if mf.permanent
                          else "failed_retryable")
                    tracing.event("decom.move.failed", bucket=bucket,
                                  obj=name, error=str(mf),
                                  permanent=mf.permanent)
            if not obj_failed:
                # object-granular checkpoint: the cursor records only
                # FULLY moved objects (source delete landed), so a
                # resume can never skip an in-flight move
                self.state["cursor"] = {"bucket": bucket, "obj": name}
                self._since_ckpt += 1
                if self._since_ckpt >= self.checkpoint_every:
                    self._since_ckpt = 0
                    self._save()

    def _move_version(self, bucket: str, name: str, oi) -> None:
        """One version move with MRF-style retry: permanent failures
        (and gone-mid-drain versions) never spin, retryable ones back
        off a few rounds before the object is recorded failed (a
        restarted drain retries it — convergence over completeness)."""
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            if self._stop.is_set():
                raise MoveFailed("drain canceled", permanent=False)
            try:
                with scope(Budget(self.obj_timeout)):
                    target = self._target_pool(name, max(oi.size, 0))
                    move_version(self.src, target, bucket, name, oi)
                return
            except _GONE:
                # deleted mid-drain by a client: nothing left to move
                return
            except Exception as e:
                last = e
                kind = _classify(e)
                if kind == "gone":
                    return
                if kind == "permanent":
                    raise MoveFailed(str(e), permanent=True)
                if attempt < self.retries:
                    self.state["retried_objects"] += 1
                    _bump("retries")
                    tracing.event("decom.move.retry", bucket=bucket,
                                  obj=name, attempt=attempt + 1)
                    self._stop.wait(0.1 * (2 ** attempt))
        raise MoveFailed(str(last), permanent=False)

    def _target_pool(self, obj: str, size: int):
        avail = self.pools._pool_available(obj, size)
        best, best_a = None, -1
        for i, (p, a) in enumerate(zip(self.pools.pools, avail)):
            if i == self.idx:
                continue
            if a > best_a:
                best, best_a = p, a
        if best is None or best_a <= 0:
            raise errors.DiskFull("no target pool has space")
        return best


def _dest_version(target, bucket: str, name: str, oi):
    """ObjectInfo of the destination's copy of this version (delete
    markers included), or None when the destination has nothing for it.
    For versioned objects the version id is the identity; for the null
    version the latest null-version info answers."""
    from minio_tpu.erasure.objects import MethodNotAllowedDeleteMarker

    try:
        return target.get_object_info(bucket, name,
                                      version_id=oi.version_id or "")
    except MethodNotAllowedDeleteMarker as e:
        return e.object_info
    except (errors.ObjectNotFound, errors.VersionNotFound):
        return None
    except errors.MethodNotAllowed:
        return None


def _dest_has_same_or_newer(target, bucket: str, name: str, oi) -> bool:
    """True when the destination already holds this version (or, for
    the null version, a same-or-newer one): the source copy is stale
    and must be DROPPED, never copied over the destination (the
    model's copy-clobbers-newer mutation is exactly this check
    removed)."""
    info = _dest_version(target, bucket, name, oi)
    if info is None:
        return False
    if oi.version_id:
        return True  # exact version already committed at the dest
    return (info.mod_time or 0) >= (oi.mod_time or 0)


def _fence(src, bucket: str, name: str) -> None:
    """The write-fence's invalidation half: fire the SOURCE set's
    ns_updated choke point (hot tier, metacache, bloom tracker — and
    via the PR 8 broadcast, every peer's hot tier) BEFORE the source
    copy dies, so no cached route can outlive the version it points
    at."""
    try:
        es = src.get_hashed_set(name)
    except Exception:
        return
    hook = getattr(es, "ns_updated", None)
    if hook is not None:
        try:
            hook(bucket, name)
        except Exception:
            pass


def move_version(src, target, bucket: str, name: str, oi) -> None:
    """Move one object version between pools with its version id and
    mod time pinned — shared by decommission and rebalance.

    Write-fence ordering (models/topology.py): (1) commit the copy at
    the destination with write quorum, (2) fire invalidation, (3) only
    then delete the source copy.  A destination that already holds the
    version same-or-newer skips (1) — the source copy is stale."""
    from minio_tpu.erasure.objects import PutObjectOptions

    if oi.delete_marker:
        if not _dest_has_same_or_newer(target, bucket, name, oi):
            # replay the marker with its id + mod time pinned
            target.put_delete_marker(bucket, name, oi.version_id or "",
                                     oi.mod_time)
        _fence(src, bucket, name)
        src.delete_object(bucket, name,
                          version_id=oi.version_id or "null")
        return
    if _dest_has_same_or_newer(target, bucket, name, oi):
        # an overwrite PUT landed at a live pool mid-drain: the source
        # copy is stale — drop it, never clobber the newer destination
        _bump("skipped_stale")
        _fence(src, bucket, name)
        src.delete_object(bucket, name, version_id=oi.version_id or "null")
        return
    _, stream = src.get_object(bucket, name, version_id=oi.version_id)
    meta = {k: v for k, v in oi.metadata.items()
            if k not in ("etag", "content-type")}
    opts = PutObjectOptions(
        user_metadata=meta,
        content_type=oi.content_type,
        versioned=bool(oi.version_id),
        version_id=oi.version_id,
        mod_time=oi.mod_time,
        # carry the ETag verbatim: a multipart (md5-N) or SSE/compressed
        # ETag recomputed from the drained stream would differ and break
        # If-Match / client caches (ADVICE r4 medium)
        etag=oi.etag or oi.metadata.get("etag", ""),
    )
    # put_object raising means the copy did NOT meet write quorum: the
    # exception propagates and the source copy survives (no-version-
    # lost) — the retry loop or a restarted drain converges it
    target.put_object(bucket, name, _IterReader(stream), oi.size, opts)
    _fence(src, bucket, name)
    src.delete_object(bucket, name, version_id=oi.version_id or "null")


class PoolRebalance:
    """Spread existing objects so pool fill fractions converge — run
    after expanding a deployment with a new (empty) pool (reference
    cmd/erasure-server-pool-rebalance.go; `mc admin rebalance start`).

    Pools whose used fraction exceeds the cluster average by more than
    `tolerance` donate objects to the emptiest pool until they fall
    within it.  Moves share the decommission's fenced move_version (and
    its stale-source protection), defer to foreground load through the
    same brownout throttle, and run each move under a deadline budget.
    """

    def __init__(self, pools, tolerance: float = 0.02):
        if len(pools.pools) < 2:
            raise errors.InvalidArgument("rebalance needs multiple pools")
        self.pools = pools
        self.tolerance = tolerance
        # rebalance meta lives on the FIRST pool's drives, quorum-written
        # like decom state (reference rebalanceMeta under .minio.sys)
        self.state = load_state(pools.pools[0], REBAL_FILE)
        if self.state.get("state") == "running":
            # persisted 'running' with no thread = a previous process
            # died mid-rebalance; surface that instead of lying.  A
            # start() from here resumes (rebalance is idempotent: it
            # re-measures fill fractions and moves only what is still
            # over tolerance).
            self.state["state"] = "interrupted"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.throttle = None
        self.obj_timeout = float(os.environ.get(
            "MINIO_TPU_DECOM_OBJ_TIMEOUT_S", "120"))
        self.retries = max(0, int(os.environ.get(
            "MINIO_TPU_DECOM_RETRIES", "3")))
        self.checkpoint_every = max(1, int(os.environ.get(
            "MINIO_TPU_DECOM_CHECKPOINT_EVERY", "32")))
        # test-only crash injection, same contract as the drain's hook:
        # fn(moved_objects) -> True kills the rebalance thread without
        # a final save (simulated SIGKILL mid-donation)
        self._crash_hook = None
        self._since_ckpt = 0

    def _save(self) -> None:
        self.state["degraded"] = False
        if not save_state(self.pools.pools[0], self.state, REBAL_FILE):
            self.state["degraded"] = True

    # -- capacity math ------------------------------------------------------
    def _capacity(self, fresh: bool = False) -> list[tuple[int, int]]:
        """[(total, used)] per pool; fresh=True re-measures past any
        usage caches (the convergence loop must see its own moves)."""
        out = []
        for p in self.pools.pools:
            total = used = 0
            for d in p.all_disks:
                try:
                    if d is None or not d.is_online():
                        continue
                    if fresh:
                        inner = getattr(d, "_inner", d)
                        inv = getattr(inner, "invalidate_usage_cache", None)
                        if inv is not None:
                            inv()
                    di = d.disk_info()
                    total += di.total
                    used += di.used
                except Exception:
                    continue
            out.append((total, used))
        return out

    def _fractions(self) -> list[float]:
        return [u / t if t else 0.0 for t, u in self._capacity()]

    def status(self) -> dict:
        return {**self.state, "fill": [round(f, 4)
                                       for f in self._fractions()]}

    # -- control ------------------------------------------------------------
    def start(self) -> None:
        if self.state.get("state") == "running":
            raise errors.InvalidArgument("rebalance already running")
        # a restart after a mid-donation crash resumes the namespace
        # walk from the quorum-persisted per-donor cursors instead of
        # replaying every bucket scan from the top; anything else
        # (fresh start, completed run) scans from scratch
        cursors = dict(self.state.get("cursors") or {}) \
            if self.state.get("state") == "interrupted" else {}
        self.state = {"state": "running", "started": time.time(),
                      "moved_objects": 0, "moved_bytes": 0,
                      "failed_objects": 0, "throttle_waits": 0,
                      "cursors": cursors,
                      "seq": int(self.state.get("seq", 0))}
        self._save()
        self._stop.clear()
        self._thread = service_thread(self._run, name="pool-rebalance")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.state.get("state") == "running":
            self.state["state"] = "stopped"
        self._save()

    def wait(self, timeout: float = 600.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _throttle_wait(self) -> None:
        if self.throttle is None or self.throttle():
            return
        self.state["throttle_waits"] += 1
        _bump("throttle_waits")
        while not self._stop.is_set() and not self.throttle():
            time.sleep(0.05)

    # -- loop ---------------------------------------------------------------
    def _run(self) -> None:
        root = tracing.start("topology.rebalance")
        token = tracing.install(root) if root is not None else None
        t0 = time.monotonic()
        status = 200
        try:
            try:
                for _ in range(4):  # bounded convergence rounds
                    if self._stop.is_set():
                        break
                    caps = self._capacity(fresh=True)
                    fracs = [u / t if t else 0.0 for t, u in caps]
                    avg = sum(fracs) / len(fracs)
                    suspended = self.pools.topology.suspended()
                    donors = [i for i, f in enumerate(fracs)
                              if f > avg + self.tolerance
                              and i not in suspended]
                    if not donors:
                        break
                    moved_any = False
                    for i in donors:
                        # byte budget computed up front: the du cache lags
                        # moves, so steering by live fractions over-drains
                        over = int((fracs[i] - avg) * caps[i][0])
                        if self._donate(i, over, fracs):
                            moved_any = True
                    self._save()
                    if not moved_any:
                        break
                self.state["state"] = "complete"
                self.state["finished"] = time.time()
                # converged: drop resume cursors so a future rebalance
                # walks the (changed) namespace from the top
                self.state.pop("cursors", None)
            except _DrainKilled:
                status = 500
                return  # crash injection: NO save (simulated SIGKILL)
            except Exception as e:
                self.state["state"] = "failed"
                self.state["error"] = str(e)
                status = 500
            self._save()
        finally:
            if root is not None:
                root.tag(moved=self.state.get("moved_objects", 0))
                tracing.reset(token)
                tracing.finish(root, status=status, error=status >= 500,
                               duration=time.monotonic() - t0)

    def _donate(self, idx: int, budget: int, fracs: list[float]) -> bool:
        """Move ~`budget` logical bytes out of pool `idx` into the
        emptiest other pools; True if anything moved."""
        src = self.pools.pools[idx]
        caps = self._capacity()
        est = list(fracs)  # locally-updated estimates
        donated = 0
        moved = 0
        # erasure overhead: logical bytes land ~N/K larger on disk
        overhead = 2.0
        suspended = self.pools.topology.suspended()
        # object-granular resume: the quorum-persisted cursor records
        # the last FULLY donated object (all versions moved, source
        # deletes landed), so a killed rebalance restarts its walk
        # right after it instead of replaying the whole bucket scan
        cursors = self.state.setdefault("cursors", {})
        cur = cursors.get(str(idx)) or {}
        for vol in sorted(src.list_buckets(), key=lambda v: v.name):
            bucket = vol.name
            if cur and bucket < cur.get("bucket", ""):
                continue  # donor walked past this bucket pre-crash
            start_after = cur.get("obj", "") \
                if cur.get("bucket") == bucket else ""
            for entry in src.list_entries(bucket):
                if self._stop.is_set() or donated >= budget:
                    return moved > 0
                name = entry.name
                if start_after and name <= start_after:
                    continue  # already donated before the crash
                self._throttle_wait()
                if self._crash_hook is not None \
                        and self._crash_hook(self.state["moved_objects"]):
                    raise _DrainKilled()
                tgt_i = min(
                    (i for i in range(len(est)) if i != idx
                     and i not in suspended),
                    key=lambda i: est[i], default=None)
                if tgt_i is None:
                    return moved > 0
                target = self.pools.pools[tgt_i]
                try:
                    obj_bytes = 0
                    for oi in reversed(entry.versions):
                        with scope(Budget(self.obj_timeout)):
                            move_version(src, target, bucket, name,
                                         oi)
                        self.state["moved_objects"] += 1
                        self.state["moved_bytes"] += max(oi.size, 0)
                        obj_bytes += max(oi.size, 0)
                    moved += 1
                    donated += int(obj_bytes * overhead)
                    if caps[tgt_i][0]:
                        est[tgt_i] += obj_bytes * overhead / caps[tgt_i][0]
                except _GONE:
                    continue  # deleted mid-rebalance: nothing to move
                except Exception:
                    self.state["failed_objects"] += 1
                    continue  # cursor stays put: a restart retries it
                cursors[str(idx)] = {"bucket": bucket, "obj": name}
                self._since_ckpt += 1
                if self._since_ckpt >= self.checkpoint_every:
                    self._since_ckpt = 0
                    self._save()
        # full namespace walked: a future rebalance starts fresh
        cursors.pop(str(idx), None)
        return moved > 0


class _IterReader(io.RawIOBase):
    """File-like over the get_object chunk iterator."""

    def __init__(self, chunks):
        self._it = iter(chunks)
        self._buf = b""

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            out = self._buf + b"".join(self._it)
            self._buf = b""
            return out
        while len(self._buf) < n:
            try:
                self._buf += next(self._it)
            except StopIteration:
                break
        out, self._buf = self._buf[:n], self._buf[n:]
        return out
