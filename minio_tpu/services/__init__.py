"""Background services: MRF heal queue, heal sequences, data scanner.

ServiceManager wires them onto an object layer the way serverMain starts
initAutoHeal/initHealMRF/initDataScanner (cmd/server-main.go:528-585).
"""

from __future__ import annotations

from .heal import (BackgroundHealer, HealManager, HealSequence,
                   HealSequenceStatus, heal_fresh_disks,
                   load_healing_tracker, mark_disk_healing)
from .mrf import MRFQueue
from .monitor import DriveMonitor
from .scanner import BucketUsage, DataScanner, DataUsageInfo


class ServiceManager:
    """Owns the background workers for one server process."""

    def __init__(self, object_layer, scan_interval: float = 60.0,
                 heal_interval: float = 3600.0, lifecycle_fn=None,
                 monitor_interval: float = 10.0):
        from minio_tpu.utils.bloom import DataUpdateTracker

        self.ol = object_layer
        self.mrf = MRFQueue(object_layer)
        self.heals = HealManager(object_layer)
        self.tracker = DataUpdateTracker()
        self.scanner = DataScanner(object_layer, interval=scan_interval,
                                   heal_queue=self.mrf.enqueue,
                                   lifecycle_fn=lifecycle_fn,
                                   tracker=self.tracker)
        self.bg_heal = BackgroundHealer(object_layer, interval=heal_interval)
        self.monitor = DriveMonitor(object_layer,
                                    interval=monitor_interval)
        self.replication = None  # ReplicationPool, wired by attach_services
        self.tier = None         # TierManager, wired by attach_services
        self._attach_heal_queue()

    def _attach_heal_queue(self) -> None:
        """Point every erasure set's async-heal hook at the MRF queue and
        its change hook at the update tracker."""
        from minio_tpu.erasure.objects import add_ns_update_hook

        for pool in getattr(self.ol, "pools", [self.ol]):
            for es in getattr(pool, "sets", []):
                es.heal_queue = self.mrf.enqueue
        add_ns_update_hook(self.ol, self.tracker.mark)

    def close(self) -> None:
        self.scanner.close()
        self.bg_heal.close()
        self.monitor.close()
        self.mrf.close()
        if self.replication is not None:
            self.replication.close()
        if self.tier is not None:
            self.tier.close()


__all__ = [
    "BackgroundHealer", "BucketUsage", "DataScanner", "DataUsageInfo",
    "DriveMonitor", "HealManager", "HealSequence", "HealSequenceStatus",
    "MRFQueue", "ServiceManager", "heal_fresh_disks",
    "load_healing_tracker", "mark_disk_healing",
]
