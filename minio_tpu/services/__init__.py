"""Background services: MRF heal queue, heal sequences, data scanner.

ServiceManager wires them onto an object layer the way serverMain starts
initAutoHeal/initHealMRF/initDataScanner (cmd/server-main.go:528-585).
"""

from __future__ import annotations

from .brownout import BrownoutController
from .heal import (BackgroundHealer, HealManager, HealSequence,
                   HealSequenceStatus, heal_fresh_disks,
                   load_healing_tracker, mark_disk_healing)
from .mrf import MRFQueue
from .monitor import DriveMonitor
from .scanner import BucketUsage, DataScanner, DataUsageInfo


class ServiceManager:
    """Owns the background workers for one server process."""

    def __init__(self, object_layer, scan_interval: float = 60.0,
                 heal_interval: float = 3600.0, lifecycle_fn=None,
                 monitor_interval: float | None = None):
        import os

        from minio_tpu.utils.bloom import DataUpdateTracker

        if monitor_interval is None:
            monitor_interval = float(
                os.environ.get("MINIO_TPU_MONITOR_INTERVAL", "10"))
        self.ol = object_layer
        # brownout plane: the API front feeds pressure in, every
        # background worker asks permission before spending drive IOPs
        self.brownout = BrownoutController()
        self.mrf = MRFQueue(object_layer)
        self.mrf.throttle = self.brownout.background_allowed
        self.heals = HealManager(object_layer)
        self.tracker = DataUpdateTracker()
        self.scanner = DataScanner(object_layer, interval=scan_interval,
                                   heal_queue=self.mrf.enqueue,
                                   lifecycle_fn=lifecycle_fn,
                                   tracker=self.tracker)
        self.scanner.throttle = self.brownout.background_allowed
        self.bg_heal = BackgroundHealer(object_layer, interval=heal_interval)
        self.bg_heal.throttle = self.brownout.background_allowed
        self.monitor = DriveMonitor(object_layer,
                                    interval=monitor_interval)
        self.replication = None  # ReplicationPool, wired by attach_services
        self.tier = None         # TierManager, wired by attach_services
        self.drive_resyncs = 0      # breaker recoveries that kicked a re-sync
        self.resync_objects = 0     # objects enqueued by those re-syncs
        # flap damping: a drive bouncing on a bad NIC must not trigger a
        # full-set enqueue per bounce (MRF already dedups pending tasks;
        # this bounds the LISTING work too)
        self._resync_min_interval = float(
            os.environ.get("MINIO_TPU_RESYNC_MIN_INTERVAL", "60"))
        self._last_resync: dict = {}  # drive endpoint -> monotonic ts
        self._resync_deferred: set = set()  # endpoints with a sweep queued
        import threading as _threading
        self._resync_mu = _threading.Lock()
        # set by close(): wakes deferred re-sync waits so they exit
        # instead of firing listings/enqueues against torn-down services
        self._closing = _threading.Event()
        self._attach_heal_queue()
        # multi-process data plane (ISSUE 8): when MINIO_TPU_WORKERS is
        # set, warm the worker/hash-lane processes at boot so the first
        # PUT does not pay the spawn+import cost.  The plane never
        # enqueues background work — heal/scanner/MRF keep the
        # in-process path — so brownout throttling needs no new wiring:
        # worker jobs exist only downstream of foreground PUTs the
        # admission plane already meters.
        from minio_tpu.parallel import workers as _workers

        if _workers.worker_count() > 0:
            _workers.get_plane()

    def _attach_heal_queue(self) -> None:
        """Point every erasure set's async-heal hook at the MRF queue, its
        change hook at the update tracker, and every health-tracked
        drive's reconnect hook at the MRF re-sync."""
        from minio_tpu.erasure.objects import add_ns_update_hook

        for pool in getattr(self.ol, "pools", [self.ol]):
            for es in getattr(pool, "sets", []):
                es.heal_queue = self.mrf.enqueue
                for d in getattr(es, "disks", []):
                    if d is not None and hasattr(d, "health_stats"):
                        # bind the OWNING set: only its objects can have
                        # shards on this drive, so the re-sync is scoped
                        # to it, not the whole namespace
                        d.on_online = (
                            lambda drv, _es=es: self._drive_reconnected(
                                drv, _es))
        add_ns_update_hook(self.ol, self.tracker.mark)

    def _drive_reconnected(self, drive, es) -> None:
        """Breaker-recovery hook: writes that met quorum while this drive
        was offline are missing their shard here — enqueue the owning
        erasure set's objects for MRF heal so the drive converges
        (reference: the MRF queue absorbs partial writes, cmd/mrf.go;
        reconnect kicks re-sync)."""
        import time as _time

        from minio_tpu.services.heal import _set_buckets
        from minio_tpu.utils.deadline import service_thread
        from minio_tpu.utils.logger import log

        if self._closing.is_set():
            return
        try:
            ep = drive.endpoint()
        except Exception:
            ep = str(id(drive))
        now = _time.monotonic()
        wait = self._resync_min_interval - \
            (now - self._last_resync.get(ep, -1e9))
        if wait > 0:
            # Flap damping bounds the LISTING churn of a drive bouncing
            # on a bad NIC — but a swallowed re-sync must still HAPPEN.
            # on_online fires only on the offline->online transition, so
            # dropping this call outright would leave writes that landed
            # after the previous sweep unconverged forever (the cluster
            # -boot probe race reliably consumed the damping budget just
            # before a real recovery).  Defer one sweep per endpoint to
            # the end of the window instead.
            with self._resync_mu:
                if ep in self._resync_deferred:
                    return
                self._resync_deferred.add(ep)

            def _deferred():
                if self._closing.wait(wait):
                    return  # shutting down: drop, don't fire
                with self._resync_mu:
                    self._resync_deferred.discard(ep)
                self._drive_reconnected(drive, es)

            service_thread(_deferred, name="mrf-resync-defer")
            return
        self._last_resync[ep] = now
        try:
            log.info("drive back online, MRF re-sync", endpoint=ep)
        except Exception:
            pass
        n = 0
        try:
            for bucket in _set_buckets(es):
                try:
                    objs = es.list_objects(bucket)
                except Exception:
                    continue
                for o in objs:
                    self.mrf.enqueue(bucket, o)
                    n += 1
        except Exception:
            pass
        # several drives can reconnect at once (one probe thread each);
        # the bare += is a read-modify-write that loses counts
        with self._resync_mu:
            self.drive_resyncs += 1
            self.resync_objects += n

    def close(self) -> None:
        self._closing.set()
        self.scanner.close()
        self.bg_heal.close()
        self.monitor.close()
        self.mrf.close()
        if self.replication is not None:
            self.replication.close()
        if self.tier is not None:
            self.tier.close()
        # tear down the worker plane (processes + shm rings).  The
        # plane is a process-wide singleton: another still-open server
        # in this process lazily restarts it on its next eligible PUT,
        # so closing here is always safe and guarantees zero leaked
        # processes/segments after the LAST server shuts down.
        from minio_tpu.parallel import workers as _workers

        _workers.shutdown_plane()


__all__ = [
    "BackgroundHealer", "BucketUsage", "DataScanner", "DataUsageInfo",
    "DriveMonitor", "HealManager", "HealSequence", "HealSequenceStatus",
    "MRFQueue", "ServiceManager", "heal_fresh_disks",
    "load_healing_tracker", "mark_disk_healing",
]
