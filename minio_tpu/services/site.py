"""Site replication: IAM + bucket-configuration sync across clusters.

Reference: cmd/site-replication.go (~2.8k LoC) — a set of peer clusters
keep buckets, IAM (users/groups/policies/mappings) and bucket metadata
(policy, lifecycle, SSE, lock, tags, quota, versioning) converged:
every local mutation is pushed to every peer, and adding a peer
triggers a full initial sync.

Wire protocol here: each push is a signed POST to the peer's
`/minio/admin/v3/site-replication/apply` endpoint carrying
{kind, ...payload} JSON; the receiving side applies it with
propagation SUPPRESSED (a **contextvar**, sibling of deadline.Budget
and the tracing span — `ctx_submit`/copied contexts carry it across
executor hops, where the old `threading.local` silently dropped it and
an apply that fanned out through the pool could re-push to peers and
loop).  Pushes are queued and retried by a background worker, so a
temporarily-down peer converges when it returns.

Resync (ISSUE 14): `resync(peer)` re-pushes bucket state to one peer —
driven by the bloom change tracker (utils/bloom.py) so only buckets
that CAN have changed since the last scanner cycle are walked, not the
full namespace (reference: site replication resync,
cmd/site-replication.go; the tracker is the same one the scanner uses
to skip clean subtrees).
"""

from __future__ import annotations

import contextvars
import http.client
import json
import queue
import threading
import time
import urllib.parse

from minio_tpu.storage import errors
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import service_thread
from minio_tpu.storage.local import SYSTEM_VOL
from minio_tpu.utils.logger import log

SITE_CONFIG_PATH = "config/site.json"
APPLY_PATH = "/minio/admin/v3/site-replication/apply"
MAX_ATTEMPTS = 5

#: propagation suppression rides a contextvar so it survives
#: ctx_submit/executor hops (the threading.local it replaces did not:
#: an apply fanning out through a pool thread lost the flag and its
#: mutation hooks re-pushed to peers — a cross-site feedback loop)
_suppress: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "minio_tpu_site_suppress", default=False)


def propagation_suppressed() -> bool:
    return _suppress.get()


class _Suppressed:
    def __enter__(self):
        self._token = _suppress.set(True)
        return self

    def __exit__(self, *a):
        _suppress.reset(self._token)
        return False


class SitePeer:
    def __init__(self, name: str, endpoint: str, access_key: str,
                 secret_key: str):
        self.name = name
        self.endpoint = endpoint
        self.access_key = access_key
        self.secret_key = secret_key

    def to_dict(self, redact: bool = False) -> dict:
        d = {"name": self.name, "endpoint": self.endpoint,
             "accessKey": self.access_key}
        if not redact:
            d["secretKey"] = self.secret_key
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SitePeer":
        return cls(d["name"], d["endpoint"], d.get("accessKey", ""),
                   d.get("secretKey", ""))


class SiteReplicationSys:
    """Owns the peer registry, mutation hooks, and the push worker."""

    def __init__(self, api, meta, iam):
        self.api = api
        self.meta = meta
        self.iam = iam
        self.peers: dict[str, SitePeer] = {}
        self._mu = threading.Lock()
        self._io_lock = threading.Lock()  # orders _persist disk writes
        self._save_seq = 0
        self._persisted_seq = 0
        # one queue + worker PER PEER: a down peer's retries/timeouts
        # must never stall pushes to healthy peers
        self._queues: dict[str, queue.Queue] = {}
        self._workers: dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        # push counters are bumped from per-peer worker threads: a bare
        # += would be exactly the lost-update class PR 10's detector
        # flags — one lock owns all of them
        self._stat_mu = threading.Lock()
        self.pushed = 0
        self.failed = 0
        self.retries = 0          # re-queued push attempts
        self.resyncs = 0          # resync sweeps run
        self.resync_pushed = 0    # docs queued by resyncs
        self.resync_skipped = 0   # buckets the bloom tracker proved clean
        self._load()
        # mutation hooks (no-ops while propagation is suppressed)
        meta.on_site_change = self._on_bucket_meta
        iam.on_site_change = self._on_iam
        for name in self.peers:
            self._ensure_worker(name)

    # -- persistence ---------------------------------------------------------
    def _disks(self):
        pool = getattr(self.api, "pools", [self.api])[0]
        return [d for d in getattr(pool, "all_disks", [])
                if d is not None and d.is_online()]

    def _load(self) -> None:
        for d in self._disks():
            try:
                doc = json.loads(d.read_all(SYSTEM_VOL, SITE_CONFIG_PATH))
                self.peers = {p["name"]: SitePeer.from_dict(p)
                              for p in doc.get("peers", [])}
                return
            except (errors.StorageError, ValueError, KeyError):
                continue

    def _snapshot_locked(self) -> tuple[bytes, int]:
        """Serialize the peer table (caller holds self._mu).  The seq
        number orders concurrent persists so a stale snapshot can never
        overwrite a newer one once the disk writes happen outside the
        hot lock."""
        self._save_seq += 1
        raw = json.dumps({"peers": [p.to_dict()
                                    for p in self.peers.values()]}).encode()
        return raw, self._save_seq

    def _persist(self, raw: bytes, seq: int) -> None:
        """Write a snapshot to the system volume WITHOUT holding
        self._mu — metadata writes must not block peer-queue feeders."""
        # lint: allow(blocking-under-lock): dedicated writer-ordering lock; nothing hot contends on it
        with self._io_lock:
            if seq <= self._persisted_seq:
                return  # a newer snapshot already landed
            ok = 0
            for d in self._disks():
                try:
                    d.write_all(SYSTEM_VOL, SITE_CONFIG_PATH, raw)
                    ok += 1
                except errors.StorageError:
                    continue
            if ok:
                # only a snapshot that actually reached a disk may
                # supersede older pending ones
                self._persisted_seq = seq

    # -- worker --------------------------------------------------------------
    def _ensure_worker(self, peer_name: str) -> None:
        with self._mu:
            q = self._queues.get(peer_name)
            if q is None:
                q = queue.Queue()
                self._queues[peer_name] = q
            t = self._workers.get(peer_name)
            if t is not None and t.is_alive():
                return
            t = service_thread(self._run, peer_name, q, start=False,
                               name=f"site-replication-{peer_name}")
            self._workers[peer_name] = t
        t.start()

    def _run(self, peer_name: str, q: queue.Queue) -> None:
        while not self._stop.is_set():
            try:
                item = q.get(timeout=0.3)
            except queue.Empty:
                continue
            if item is None:
                return
            doc, attempts = item
            with self._mu:
                peer = self.peers.get(peer_name)
            if peer is None:
                return  # peer removed: drop its queue
            try:
                self._post(peer, doc)
                with self._stat_mu:
                    self.pushed += 1
            except Exception as e:
                if attempts + 1 < MAX_ATTEMPTS:
                    with self._stat_mu:
                        self.retries += 1
                    self._stop.wait(0.5 * (2 ** attempts))
                    q.put((doc, attempts + 1))
                else:
                    with self._stat_mu:
                        self.failed += 1
                    log.warning("site replication push failed",
                                peer=peer_name, kind=doc.get("kind"),
                                error=str(e))

    def _post(self, peer: SitePeer, doc: dict) -> None:
        from minio_tpu.server import sigv4

        body = json.dumps(doc).encode()
        ep = peer.endpoint
        tls = ep.startswith("https://")
        netloc = ep.split("://", 1)[-1].rstrip("/")
        headers = {"host": netloc, "content-type": "application/json"}
        signed = sigv4.sign_request("POST", APPLY_PATH, [], headers, body,
                                    peer.access_key, peer.secret_key)
        host, _, port = netloc.partition(":")
        cls = http.client.HTTPSConnection if tls \
            else http.client.HTTPConnection
        conn = cls(host, int(port or (443 if tls else 80)), timeout=15)
        try:
            conn.request("POST", APPLY_PATH, body=body, headers=signed)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"peer {peer.name} returned {resp.status}: "
                    f"{data[:200]!r}")
        finally:
            conn.close()

    def _broadcast(self, doc: dict) -> None:
        with self._mu:
            names = list(self.peers)
        for name in names:
            self._ensure_worker(name)
            self._queues[name].put((doc, 0))

    # -- peer management -----------------------------------------------------
    def add_peers(self, peers: list[SitePeer]) -> None:
        with self._mu:
            for p in peers:
                if not p.name or not p.endpoint:
                    raise ValueError("peer name and endpoint required")
                self.peers[p.name] = p
            raw, seq = self._snapshot_locked()
        self._persist(raw, seq)
        for p in peers:
            self._ensure_worker(p.name)
            self._initial_sync(p.name)

    def remove_peer(self, name: str) -> None:
        with self._mu:
            if name not in self.peers:
                raise KeyError(name)
            del self.peers[name]
            raw, seq = self._snapshot_locked()
        self._persist(raw, seq)

    def info(self) -> dict:
        with self._mu:
            peers = [p.to_dict(redact=True) for p in self.peers.values()]
            queued = sum(q.qsize() for q in self._queues.values())
        with self._stat_mu:
            return {
                "peers": peers,
                "pushed": self.pushed, "failed": self.failed,
                "retries": self.retries,
                "resyncs": self.resyncs,
                "resyncPushed": self.resync_pushed,
                "resyncSkipped": self.resync_skipped,
                "queued": queued,
            }

    # -- mutation hooks ------------------------------------------------------
    def _on_bucket_meta(self, bucket: str) -> None:
        if propagation_suppressed() or not self.peers:
            return
        try:
            doc = self.api.get_bucket_metadata(bucket)
        except Exception:
            return
        self._broadcast({"kind": "bucket-meta", "bucket": bucket,
                         "meta": doc})

    def on_bucket_created(self, bucket: str) -> None:
        if propagation_suppressed() or not self.peers:
            return
        self._broadcast({"kind": "bucket-create", "bucket": bucket})

    def on_bucket_deleted(self, bucket: str) -> None:
        if propagation_suppressed() or not self.peers:
            return
        self._broadcast({"kind": "bucket-delete", "bucket": bucket})

    def _on_iam(self, kind: str, name: str) -> None:
        if propagation_suppressed() or not self.peers:
            return
        doc = self._export_iam(kind, name)
        if doc is not None:
            self._broadcast(doc)

    def _export_iam(self, kind: str, name: str) -> dict | None:
        if kind == "user":
            ident = self.iam.users.get(name)
            if ident is None:
                return {"kind": "iam-user-delete", "name": name}
            if ident.kind in ("svc", "sts"):
                return None  # service/STS creds stay site-local
            return {"kind": "iam-user", "name": name,
                    "secretKey": ident.secret_key,
                    "policies": list(ident.policies),
                    "enabled": ident.status != "disabled"}
        if kind == "policy":
            from minio_tpu.iam.sys import CANNED_POLICIES

            if name in CANNED_POLICIES:
                return None  # canned policies exist on every site
            pol = self.iam.get_policy(name)
            if pol is None:
                return {"kind": "iam-policy-delete", "name": name}
            return {"kind": "iam-policy", "name": name,
                    "doc": pol.to_json()}
        if kind == "group":
            g = self.iam.groups.get(name)
            if g is None:
                return {"kind": "iam-group-delete", "name": name}
            return {"kind": "iam-group", "name": name,
                    "members": sorted(g.get("members", [])),
                    "policies": list(g.get("policies", []))}
        return None

    # -- apply (receiving side) ----------------------------------------------
    def apply(self, doc: dict) -> None:
        """Apply one pushed mutation locally with propagation OFF."""
        kind = doc.get("kind", "")
        with _Suppressed():
            if kind == "bucket-create":
                try:
                    self.api.make_bucket(doc["bucket"])
                except errors.BucketExists:
                    pass
            elif kind == "bucket-delete":
                try:
                    self.api.delete_bucket(doc["bucket"], force=False)
                except (errors.BucketNotFound, errors.BucketNotEmpty):
                    pass
            elif kind == "bucket-meta":
                bucket = doc["bucket"]
                if not self.api.bucket_exists(bucket):
                    try:
                        self.api.make_bucket(bucket)
                    except errors.BucketExists:
                        pass
                self.api.set_bucket_metadata(bucket, doc.get("meta", {}))
                self.meta.invalidate(bucket)
            elif kind == "iam-user":
                prev = self.iam.users.get(doc["name"])
                prev_groups = list(prev.groups) if prev is not None else []
                self.iam.add_user(doc["name"], doc["secretKey"],
                                  doc.get("policies", []))
                ident = self.iam.users.get(doc["name"])
                if ident is not None and prev_groups:
                    # group membership is tracked on both sides; add_user
                    # built a fresh Identity — keep the local memberships
                    ident.groups = prev_groups
                self.iam.set_user_status(doc["name"],
                                         enabled=doc.get("enabled", True))
            elif kind == "iam-user-delete":
                try:
                    self.iam.remove_user(doc["name"])
                except Exception:
                    pass
            elif kind == "iam-policy":
                self.iam.set_policy(doc["name"], doc["doc"])
            elif kind == "iam-policy-delete":
                try:
                    self.iam.delete_policy(doc["name"])
                except Exception:
                    pass
            elif kind == "iam-group":
                name = doc["name"]
                want = set(doc.get("members", []))
                have = set(self.iam.groups.get(name, {})
                           .get("members", []))
                to_add = sorted(want - have)
                to_remove = sorted(have - want)
                if to_add:
                    self.iam.add_group_members(name, to_add)
                if to_remove:
                    self.iam.remove_group_members(name, to_remove)
                pols = doc.get("policies", [])
                if pols or name in self.iam.groups:
                    try:
                        self.iam.attach_group_policy(name, pols)
                    except Exception:
                        pass
            elif kind == "iam-group-delete":
                try:
                    g = self.iam.groups.get(doc["name"], {})
                    members = sorted(g.get("members", []))
                    if members:
                        self.iam.remove_group_members(doc["name"], members)
                except Exception:
                    pass
            else:
                raise ValueError(f"unknown site-replication kind {kind!r}")

    # -- initial sync / resync -----------------------------------------------
    def _sync_iam(self, peer_name: str) -> int:
        """Queue the full IAM state for one peer; returns docs queued."""
        n = 0
        for name in self.iam.list_policies():
            doc = self._export_iam("policy", name)
            if doc:
                self._queues[peer_name].put((doc, 0))
                n += 1
        for u in self.iam.list_users():
            doc = self._export_iam("user", u.get("accessKey", ""))
            if doc:
                self._queues[peer_name].put((doc, 0))
                n += 1
        for g in self.iam.list_groups():
            doc = self._export_iam("group", g)
            if doc:
                self._queues[peer_name].put((doc, 0))
                n += 1
        return n

    def _initial_sync(self, peer_name: str) -> None:
        """Queue the full local state for a newly-added peer
        (reference: site replication bootstraps buckets + IAM)."""
        try:
            self._sync_iam(peer_name)
            self.resync(peer_name, tracker=None, full=True)
        except Exception as e:
            log.warning("site replication initial sync failed",
                        peer=peer_name, error=str(e))

    def resync(self, peer_name: str, tracker=None,
               full: bool = False) -> dict:
        """Re-push bucket state to one peer (reference: `mc admin
        replicate resync`, cmd/site-replication.go) — a peer that was
        down past the push retry budget converges here without a full
        namespace walk: buckets the bloom change tracker
        (utils/bloom.py) proves untouched since the last scanner cycle
        are SKIPPED (false positives re-push harmlessly, false
        negatives are impossible by the filter's contract).  full=True
        (or no tracker) pushes everything.  Pushes ride the normal
        retried signed-push worker."""
        with self._mu:
            if peer_name not in self.peers:
                raise KeyError(peer_name)
        self._ensure_worker(peer_name)
        root = tracing.start("site.resync", peer=peer_name,
                             full=bool(full))
        token = tracing.install(root) if root is not None else None
        t0 = time.monotonic()
        pushed = skipped = 0
        status = 200
        try:
            q = self._queues[peer_name]
            for v in self.api.list_buckets():
                if tracker is not None and not full \
                        and not tracker.bucket_dirty(v.name):
                    skipped += 1
                    continue
                q.put(({"kind": "bucket-create", "bucket": v.name}, 0))
                pushed += 1
                meta = self.api.get_bucket_metadata(v.name)
                if meta:
                    q.put(({"kind": "bucket-meta", "bucket": v.name,
                            "meta": meta}, 0))
                    pushed += 1
        except Exception:
            status = 500
            raise
        finally:
            with self._stat_mu:
                self.resyncs += 1
                self.resync_pushed += pushed
                self.resync_skipped += skipped
            if root is not None:
                root.tag(queued=pushed, skippedClean=skipped)
                tracing.reset(token)
                tracing.finish(root, status=status, error=status >= 500,
                               duration=time.monotonic() - t0)
        return {"peer": peer_name, "queued": pushed,
                "skippedClean": skipped, "full": bool(full)}

    def close(self) -> None:
        self._stop.set()
        with self._mu:
            queues = list(self._queues.values())
            workers = list(self._workers.values())
        for q in queues:
            q.put(None)
        for t in workers:
            t.join(2)
