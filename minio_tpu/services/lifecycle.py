"""Lifecycle (ILM) execution: applies expiry actions during data scans.

Reference: the scanner's applyActions/applyLifecycle path
(cmd/data-scanner.go:891-1100) evaluates each scanned version against the
bucket's lifecycle config (internal/bucket/lifecycle ComputeAction) and
executes expirations through the object layer; expired delete markers and
noncurrent versions are removed, current-version expiry of a versioned
bucket writes a delete marker (cmd/bucket-lifecycle.go applyExpiryRule).

Transition actions are delegated to a pluggable `transition_fn` (wired by
the tiering subsystem); when absent they are counted but skipped.
"""

from __future__ import annotations

import time
import urllib.parse

from minio_tpu.bucket import metadata as bm
from minio_tpu.bucket.lifecycle import Action, ObjectOpts


def _parse_tags(oi) -> dict | None:
    from minio_tpu.erasure.objects import ErasureObjects

    raw = oi.metadata.get(ErasureObjects.TAGS_KEY, "") if oi.metadata else ""
    if not raw:
        return None
    try:
        return dict(urllib.parse.parse_qsl(raw))
    except ValueError:
        return None


class LifecycleRunner:
    """scanner.lifecycle_fn: (bucket, latest ObjectInfo) -> bool
    (True = the latest version was removed and must not be counted)."""

    def __init__(self, api, meta, transition_fn=None, now_fn=time.time):
        self.api = api            # object layer (pools/sets)
        self.meta = meta          # BucketMetadataSys
        self.transition_fn = transition_fn
        self.now_fn = now_fn
        self.expired = 0
        self.expired_versions = 0
        self.transitions = 0

    def _versioned(self, bucket: str) -> bool:
        return bool(self.meta.get(bucket).get(bm.VERSIONING))

    def _versions(self, bucket: str, name: str) -> list:
        from minio_tpu.erasure import listing

        return listing.resolve_entry_versions(self.api, bucket, name)

    def __call__(self, bucket: str, oi) -> bool:
        lc = self.meta.lifecycle(bucket)
        if lc is None:
            return False
        now = self.now_fn()
        name = oi.name
        tags = _parse_tags(oi)

        has_noncurrent = any(
            r.enabled and (r.noncurrent_days or r.nc_transition_days >= 0)
            for r in lc.rules
        )
        needs_versions = has_noncurrent or oi.delete_marker
        versions = self._versions(bucket, name) if needs_versions else None
        num_versions = len(versions) if versions is not None else 1

        # noncurrent versions first (their removal never affects the latest)
        if has_noncurrent and versions:
            successor_time = versions[0].mod_time
            for v in versions[1:]:
                ev = lc.compute_action(
                    ObjectOpts(
                        name=name, mod_time=v.mod_time, is_latest=False,
                        delete_marker=v.delete_marker,
                        num_versions=num_versions,
                        successor_mod_time=successor_time, tags=tags,
                    ),
                    now=now,
                )
                successor_time = v.mod_time
                if ev.action == Action.DELETE_VERSION:
                    try:
                        self.api.delete_object(bucket, name,
                                               version_id=v.version_id or "null")
                        self.expired_versions += 1
                    except Exception:
                        pass
                elif ev.action == Action.TRANSITION_VERSION and self.transition_fn:
                    try:
                        if self.transition_fn(bucket, v, ev.tier):
                            self.transitions += 1
                    except Exception:
                        pass

        ev = lc.compute_action(
            ObjectOpts(
                name=name, mod_time=oi.mod_time, is_latest=True,
                delete_marker=oi.delete_marker, num_versions=num_versions,
                tags=tags,
            ),
            now=now,
        )
        if ev.action == Action.DELETE:
            try:
                if self._versioned(bucket):
                    # versioned expiry writes a delete marker (applyExpiryRule)
                    self.api.delete_object(bucket, name, versioned=True)
                else:
                    self.api.delete_object(bucket, name)
                self.expired += 1
                return True
            except Exception:
                return False
        if ev.action == Action.DELETE_MARKER:
            try:
                self.api.delete_object(bucket, name,
                                       version_id=oi.version_id or "null")
                self.expired += 1
                return True
            except Exception:
                return False
        if ev.action == Action.TRANSITION and self.transition_fn:
            try:
                if self.transition_fn(bucket, oi, ev.tier):
                    self.transitions += 1
            except Exception:
                pass
        return False
