"""MRF ("most recently failed") heal queue.

Equivalent of the reference's in-memory partial-write queue
(cmd/mrf.go:47-60): PutObject enqueues objects whose write met quorum but
missed some drives; a background worker re-heals them shortly after.  The
read path enqueues objects observed missing/corrupt shards
(cmd/erasure-object.go:316-339, cmd/background-heal-ops.go).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from minio_tpu.storage import errors as storage_errors
from minio_tpu.utils.deadline import service_thread

# heal failures that cannot heal themselves with time: the object (or
# its bucket/version) is gone — requeueing these only burns drive IOPs
_PERMANENT = (storage_errors.ObjectNotFound, storage_errors.BucketNotFound,
              storage_errors.VersionNotFound, storage_errors.FileNotFound)


@dataclass
class MRFStats:
    enqueued: int = 0
    healed: int = 0
    failed: int = 0
    dropped: int = 0
    pending: int = 0

    def to_dict(self) -> dict:
        return {"enqueued": self.enqueued, "healed": self.healed,
                "failed": self.failed, "dropped": self.dropped,
                "pending": self.pending}


@dataclass(frozen=True)
class _HealTask:
    bucket: str
    obj: str
    version_id: str = ""
    deep: bool = False
    # requeue round (excluded from eq/hash so dedup spans rounds)
    attempts: int = field(default=0, compare=False)


class MRFQueue:
    """Bounded queue + worker thread re-healing partial writes.

    `object_layer` needs a `heal_object(bucket, obj, version_id)` method
    (ErasureObjects / ErasureSets / ErasureServerPools all provide it).
    """

    MAX_PENDING = 10000  # reference: mrfOpsQueueSize (cmd/mrf.go:29)
    # A task whose inner retries all fail re-enqueues with exponential
    # backoff up to this many rounds before counting as failed.  The
    # inner retries are 50 ms apart — far shorter than a recovering
    # drive's settle window (breaker probe + RPC timeouts are seconds),
    # so without the backoff rounds a re-sync racing a reconnect marks
    # its heals failed forever and the drive never converges.
    REQUEUE_MAX = 8

    def __init__(self, object_layer, delay: float = 0.05,
                 max_retries: int = 3):
        self.ol = object_layer
        self.delay = delay
        self.max_retries = max_retries
        self.stats = MRFStats()
        # brownout hook: callable -> bool; False pauses healing while
        # foreground load is shedding (wired by ServiceManager)
        self.throttle = None
        self._q: queue.Queue = queue.Queue(maxsize=self.MAX_PENDING)
        self._inflight: set[_HealTask] = set()
        self._backlog: list[tuple[float, _HealTask]] = []  # (due, task)
        self._active = 0  # heals currently executing (for drain)
        self._mu = threading.Lock()
        # signaled whenever the queue may have drained (task finished or
        # dropped) so drain() wakes immediately instead of busy-polling
        self._idle = threading.Condition(self._mu)
        self._stop = threading.Event()
        self._worker = service_thread(self._run, name="mrf-heal")

    # -- producer ----------------------------------------------------------
    def enqueue(self, bucket: str, obj: str, version_id: str = "",
                deep: bool = False) -> None:
        """deep=True forces a bitrot-verifying heal — the read path sets
        it when a shard failed VERIFICATION mid-stream (size-correct
        corruption is invisible to the shallow part checks)."""
        t = _HealTask(bucket, obj, version_id, deep)
        with self._mu:
            if t in self._inflight:
                return
            self._inflight.add(t)
            self.stats.enqueued += 1
        try:
            self._q.put_nowait(t)
            with self._mu:
                self.stats.pending = self._q.qsize()
        except queue.Full:
            with self._idle:
                self._inflight.discard(t)
                self.stats.dropped += 1
                self._idle.notify_all()

    # -- worker ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._mu:
                due = [t for ts, t in self._backlog if ts <= now]
                if due:
                    self._backlog = [(ts, t) for ts, t in self._backlog
                                     if ts > now]
            for t in due:
                try:
                    self._q.put_nowait(t)
                except queue.Full:
                    with self._idle:
                        self._inflight.discard(t)
                        self.stats.dropped += 1
                        self._idle.notify_all()
            try:
                t = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            # drop the dedup entry as soon as the task is picked up (like
            # the reference mrf): damage inflicted while this heal runs
            # must be re-enqueueable, not silently discarded
            with self._mu:
                self._inflight.discard(t)
                self._active += 1
            # brownout: hold the heal while foreground load is shedding —
            # the task is already claimed, so it runs as soon as the
            # controller releases
            while not self._stop.is_set():
                thr = self.throttle
                if thr is None or thr():
                    break
                time.sleep(0.02)
            # brief settle delay so in-flight renames finish (reference
            # sleeps up to a second before MRF healing)
            if self.delay:
                time.sleep(self.delay)
            ok = False
            permanent = False
            # requeue rounds make ONE attempt each — the round spacing
            # is the retry; only the first round keeps the fast inner
            # retries (they paper over in-flight rename races)
            tries = self.max_retries if t.attempts == 0 else 1
            for _ in range(tries):
                try:
                    res = self.ol.heal_object(t.bucket, t.obj,
                                              t.version_id,
                                              deep=t.deep)
                    ok = not getattr(res, "failed", False)
                except _PERMANENT:
                    ok = False
                    permanent = True
                    break
                except Exception:
                    ok = False
                if ok:
                    break
                time.sleep(self.delay)
            with self._idle:
                self._active -= 1
                if ok:
                    self.stats.healed += 1
                else:
                    nxt = _HealTask(t.bucket, t.obj, t.version_id,
                                    t.deep, t.attempts + 1)
                    if (not permanent
                            and t.attempts + 1 < self.REQUEUE_MAX
                            and not self._stop.is_set()
                            and nxt not in self._inflight):
                        # transient-looking failure (drive mid-reconnect,
                        # peer restarting): back off and try again rather
                        # than giving up forever after ~150 ms of retries
                        self._inflight.add(nxt)
                        self._backlog.append(
                            (time.monotonic()
                             + min(8.0, 0.25 * (2 ** t.attempts)), nxt))
                    else:
                        self.stats.failed += 1
                self.stats.pending = self._q.qsize()
                self._idle.notify_all()

    # -- control -----------------------------------------------------------
    def _drained(self) -> bool:
        # callers hold self._mu (the condition's lock)
        return self._q.empty() and not self._inflight and not self._active

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until the queue is empty and no task is in flight.

        Condition-variable wait signaled by the worker on every task
        completion/drop: drain wakes the instant the queue empties
        instead of burning 20 ms poll cycles (tests call this a lot)."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while not self._drained():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return True

    def close(self) -> None:
        self._stop.set()
        self._worker.join(timeout=2)
        with self._idle:
            self._idle.notify_all()  # unblock any drain() caller
