"""Drive monitor: detect replaced/fresh drives and kick healing.

Reference: cmd/erasure-sets.go:288 (monitorAndConnectEndpoints — a
background loop re-probing endpoints) + cmd/background-newdisks-heal-ops.go
(the new-disk healer that notices freshly-formatted drives, marks them
with an on-drive healing tracker, and heals the whole erasure set onto
them).  Remote drives reconnect through their RPC client's own health
probe; this loop handles the LOCAL cases: a drive directory that came
back (remounted) or came back EMPTY (replaced hardware).
"""

from __future__ import annotations

import json
import threading

from minio_tpu.storage import errors
from minio_tpu.utils.deadline import service_thread
from minio_tpu.storage.local import SYSTEM_VOL
from minio_tpu.utils.logger import log

FORMAT_FILE = "format.json"


class DriveMonitor:
    def __init__(self, pools, interval: float = 10.0, autostart: bool = True):
        self.pools = pools
        self.interval = interval
        self.healed_drives = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if autostart:
            self._thread = service_thread(self._run, name="drive-monitor")

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if getattr(self, "_paused", False):
                continue
            try:
                self.check_once()
            except Exception:
                pass

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def check_once(self) -> int:
        """One probe pass; returns the number of fresh drives healed."""
        from .heal import heal_fresh_disks, load_healing_tracker, \
            mark_disk_healing

        healed = 0
        kicked = False
        for pool in getattr(self.pools, "pools", [self.pools]):
            for es in getattr(pool, "sets", []):
                for idx, d in enumerate(es.disks):
                    if d is None or not getattr(d, "is_local", lambda: True)():
                        continue
                    if not d.is_online():
                        continue
                    try:
                        d.read_all(SYSTEM_VOL, FORMAT_FILE)
                        continue  # formatted and present
                    except errors.StorageError:
                        pass
                    # a live local drive with NO format.json: replaced
                    # hardware — re-stamp its format identity and mark it
                    # for set healing (reference background-newdisks heal)
                    if load_healing_tracker(d) is None:
                        try:
                            self._reformat(pool, es, idx, d)
                            mark_disk_healing(d)
                            kicked = True
                            log.info("fresh drive detected, healing",
                                     endpoint=d.endpoint())
                        except errors.StorageError:
                            continue
        if kicked:
            done = heal_fresh_disks(self.pools)
            healed = len(done)
            self.healed_drives += healed
        return healed

    @staticmethod
    def _reformat(pool, es, idx: int, d) -> None:
        """Write the drive's format.json from its pool's layout (the
        deployment id is pinned by the surviving drives)."""
        from minio_tpu.erasure.sets import _format_doc

        layout = [
            [f"d{s}-{i}" for i in range(pool.set_drive_count)]
            for s in range(pool.set_count)
        ]
        this = layout[es.set_index][idx] if hasattr(es, "set_index") \
            else layout[0][idx]
        d.write_all(SYSTEM_VOL, FORMAT_FILE, json.dumps(
            _format_doc(pool.deployment_id, layout, this)).encode())
        d.set_disk_id(this)

    def close(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
