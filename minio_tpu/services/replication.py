"""Async bucket replication: worker pool, remote targets, resync.

Reference: cmd/bucket-replication.go:826 (replicateObject via a worker
pool fed from replicationPool), cmd/bucket-targets.go (remote-target
registry with ARNs), delete/delete-marker replication
(cmd/bucket-replication.go replicateDelete), and resync of existing
objects.

Flow: PutObject under a matching replication rule stores
`x-minio-replication-status: PENDING` in the version's metadata and
enqueues a replicate op; a worker streams the object from the local
layer, PUTs it to the rule's remote target with replica markers, then
flips the source status to COMPLETED (FAILED after retries exhaust,
left for the next resync).  Deletes replicate as deletes (or delete
markers on versioned targets).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from dataclasses import dataclass, field

from minio_tpu.utils.deadline import service_thread
from minio_tpu.utils.s3client import S3Client, S3ClientError

# version-metadata key carrying replication state (surfaced as the
# x-amz-replication-status response header)
REPL_STATUS_KEY = "x-minio-replication-status"
# marker a replica PUT carries so the target records REPLICA status
REPLICA_HEADER = "x-minio-source-replication-request"

PENDING = "PENDING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"
REPLICA = "REPLICA"

MAX_ATTEMPTS = 3


def load_targets(meta, bucket: str) -> list[ReplicationTarget]:
    """Parse the bucket's registered remote targets — the ONE place the
    replication_targets JSON schema is interpreted (admin handlers and the
    worker pool both call this)."""
    raw = meta.get(bucket).get("replication_targets")
    if not raw:
        return []
    try:
        return [ReplicationTarget.from_dict(d) for d in json.loads(raw)]
    except (ValueError, KeyError):
        return []


@dataclass
class ReplicationTarget:
    """One remote target (reference madmin.BucketTarget)."""

    arn: str
    endpoint: str
    bucket: str
    access_key: str
    secret_key: str
    region: str = "us-east-1"
    # bytes/sec cap for replication TO this target; 0 = unlimited
    # (reference madmin.BucketTarget.BandwidthLimit)
    bandwidth_limit: int = 0

    def to_dict(self) -> dict:
        return {"arn": self.arn, "endpoint": self.endpoint,
                "bucket": self.bucket, "accessKey": self.access_key,
                "secretKey": self.secret_key, "region": self.region,
                "bandwidthLimit": self.bandwidth_limit}

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicationTarget":
        return cls(arn=d["arn"], endpoint=d["endpoint"], bucket=d["bucket"],
                   access_key=d.get("accessKey", ""),
                   secret_key=d.get("secretKey", ""),
                   region=d.get("region", "us-east-1"),
                   bandwidth_limit=int(d.get("bandwidthLimit", 0) or 0))

    def client(self) -> S3Client:
        return S3Client(self.endpoint, self.access_key, self.secret_key,
                        region=self.region)


@dataclass
class ReplicationOp:
    bucket: str
    name: str
    version_id: str = ""
    delete: bool = False
    delete_marker: bool = False
    attempts: int = 0
    not_before: float = 0.0


@dataclass
class TargetStats:
    """Per-remote-target delivery state (reference
    cmd/bucket-targets.go TargetClient health + cmd/bucket-replication-
    stats.go per-ARN counters)."""

    completed: int = 0
    failed: int = 0
    deletes: int = 0
    proxied: int = 0
    bytes_replicated: int = 0
    last_failure: float = 0.0

    def to_dict(self) -> dict:
        return {"completed": self.completed, "failed": self.failed,
                "deletes": self.deletes, "proxied": self.proxied,
                "bytesReplicated": self.bytes_replicated,
                "lastFailure": self.last_failure}


@dataclass
class ReplicationStats:
    queued: int = 0
    completed: int = 0
    failed: int = 0
    deletes: int = 0
    proxied: int = 0
    bytes_replicated: int = 0
    per_target: dict = field(default_factory=dict)  # arn -> TargetStats
    # worker threads insert targets while admin/metrics handlers iterate
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def target(self, arn: str) -> TargetStats:
        with self._lock:
            return self._target_locked(arn)

    def _target_locked(self, arn: str) -> TargetStats:
        ts = self.per_target.get(arn)
        if ts is None:
            ts = self.per_target[arn] = TargetStats()
        return ts

    def inc(self, **deltas) -> None:
        """Counter bumps under the stats lock.  Two replication
        workers, API-thread enqueues and read-proxy paths all bump
        these; the bare `+=` they used to run is a read-modify-write
        that loses updates under contention (lockset-detector finding,
        pinned by tests/test_racecheck.py)."""
        with self._lock:
            for name, n in deltas.items():
                setattr(self, name, getattr(self, name) + n)

    def inc_target(self, arn: str, last_failure: float | None = None,
                   **deltas) -> None:
        """Per-target bumps, same lock: target rows are shared by the
        same worker/API/proxy threads as the global counters."""
        with self._lock:
            ts = self._target_locked(arn)
            for name, n in deltas.items():
                setattr(ts, name, getattr(ts, name) + n)
            if last_failure is not None:
                ts.last_failure = last_failure

    def targets_snapshot(self) -> dict:
        with self._lock:
            return dict(self.per_target)

    def to_dict(self) -> dict:
        return {"queued": self.queued, "completed": self.completed,
                "failed": self.failed, "deletes": self.deletes,
                "proxied": self.proxied,
                "bytesReplicated": self.bytes_replicated,
                "targets": {arn: t.to_dict()
                            for arn, t in self.targets_snapshot().items()}}


class ReplicationPool:
    """Background replicate workers for one server process
    (reference replicationPool, cmd/bucket-replication.go bottom)."""

    def __init__(self, api, meta, workers: int = 2):
        from minio_tpu.utils.bandwidth import (BandwidthMonitor,
                                               LimiterRegistry)

        self.api = api
        self.meta = meta
        self.stats = ReplicationStats()
        # per-target throttles + moving-average monitor (reference
        # internal/bucket/bandwidth).  The configured limit is per
        # TARGET; each node paces at limit/node_count so a cluster's
        # aggregate honors it (ClusterNode sets node_count)
        self.limiters = LimiterRegistry()
        self.bw_monitor = BandwidthMonitor()
        self.node_count = 1
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._threads = [
            service_thread(self._work, name=f"replication-{i}")
            for i in range(workers)
        ]

    def close(self) -> None:
        self._stop.set()
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=2)

    # -- enqueue ------------------------------------------------------------
    def enqueue(self, op: ReplicationOp) -> None:
        self.stats.inc(queued=1)
        self._q.put(op)

    def replicate_object(self, bucket: str, name: str,
                         version_id: str = "") -> None:
        self.enqueue(ReplicationOp(bucket, name, version_id))

    def replicate_delete(self, bucket: str, name: str, version_id: str = "",
                         delete_marker: bool = False) -> None:
        self.enqueue(ReplicationOp(bucket, name, version_id, delete=True,
                                   delete_marker=delete_marker))

    def resync(self, bucket: str) -> int:
        """Enqueue every existing object of the bucket (reference
        startReplicationResync)."""
        n = 0
        for name in self.api.list_objects(bucket):
            self.replicate_object(bucket, name)
            n += 1
        return n

    # -- target registry ----------------------------------------------------
    def target_for(self, bucket: str, arn: str) -> ReplicationTarget | None:
        for t in self.targets(bucket):
            if t.arn == arn or t.bucket == arn:
                return t
        return None

    def targets(self, bucket: str) -> list[ReplicationTarget]:
        return load_targets(self.meta, bucket)

    # -- worker -------------------------------------------------------------
    def _work(self) -> None:
        while not self._stop.is_set():
            try:
                op = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if op is None:
                return
            delay = op.not_before - time.time()
            if delay > 0:
                time.sleep(min(delay, 2.0))
                if op.not_before > time.time():
                    self._q.put(op)
                    continue
            try:
                self._process(op)
            except Exception:
                op.attempts += 1
                try:
                    _, tgt = self._rule_and_target(op)
                    if tgt is not None:
                        self.stats.inc_target(
                            tgt.arn, last_failure=time.time(),
                            **({"failed": 1}
                               if op.attempts >= MAX_ATTEMPTS else {}))
                except Exception:
                    pass
                if op.attempts < MAX_ATTEMPTS:
                    op.not_before = time.time() + 0.5 * (2 ** op.attempts)
                    self._q.put(op)
                else:
                    self.stats.inc(failed=1)
                    if not op.delete:
                        self._set_status(op, FAILED)

    def _rule_and_target(self, op: ReplicationOp):
        cfg = self.meta.replication_config(op.bucket)
        if cfg is None:
            return None, None
        rule = cfg.match(op.name)
        if rule is None:
            return None, None
        tgt = self.target_for(op.bucket, rule.destination_arn) \
            or self.target_for(op.bucket, rule.target_bucket)
        return rule, tgt

    def _process(self, op: ReplicationOp) -> None:
        rule, tgt = self._rule_and_target(op)
        if rule is None or tgt is None:
            return  # config/target removed since enqueue
        client = tgt.client()
        if op.delete:
            if op.version_id and not op.delete_marker:
                # version-specific (permanent) deletes do NOT replicate:
                # replica versions get fresh ids at the target, so the
                # source vid is meaningless there, and deleting the
                # target's live version would diverge the clusters
                # (reference VersionPurgeStatus gating)
                return
            if op.delete_marker and not rule.delete_marker_replication:
                return
            if not op.delete_marker and not rule.delete_replication:
                return
            try:
                client.delete_object(tgt.bucket, op.name)
            except S3ClientError as e:
                if e.status != 404:
                    raise
            self.stats.inc(deletes=1)
            self.stats.inc_target(tgt.arn, deletes=1)
            return

        oi, stream = self.api.get_object(op.bucket, op.name,
                                         version_id=op.version_id)
        headers = {REPLICA_HEADER: "true"}
        if oi.content_type:
            headers["Content-Type"] = oi.content_type
        for k, v in (oi.metadata or {}).items():
            if k.startswith("x-amz-meta-"):
                headers[k] = v
        # transparently-compressed objects replicate as their ORIGINAL
        # bytes (the internal framing is node-local storage detail)
        from minio_tpu.utils import compress as compress_mod

        size = oi.size
        body = iter(stream)
        if oi.metadata.get(
                compress_mod.META_COMPRESSION) == compress_mod.SCHEME:
            size = int(oi.metadata.get(compress_mod.META_ACTUAL_SIZE, 0))
            body = compress_mod.decompress_stream(body)
        # stream the shards straight to the remote: no full-object
        # buffer; a configured target bandwidth limit throttles here and
        # the monitor records the target's live rate
        from minio_tpu.utils.bandwidth import ThrottledChunks

        per_node = tgt.bandwidth_limit // max(self.node_count, 1)
        body = ThrottledChunks(
            body, self.limiters.get(tgt.arn, per_node),
            on_bytes=lambda n: self.bw_monitor.record(
                op.bucket, tgt.arn, n))
        try:
            client.put_object(tgt.bucket, op.name, body,
                              headers=headers, length=size)
        finally:
            if hasattr(stream, "close"):
                stream.close()
        self.stats.inc(completed=1, bytes_replicated=size)
        self.stats.inc_target(tgt.arn, completed=1,
                              bytes_replicated=size)
        self._set_status(op, COMPLETED)

    def _set_status(self, op: ReplicationOp, status: str) -> None:
        try:
            self.api.update_object_metadata(
                op.bucket, op.name, {REPL_STATUS_KEY: status},
                version_id=op.version_id)
        except Exception:
            pass


def proxy_get(meta, bucket: str, key: str, range_header: str = "",
              stats: ReplicationStats | None = None, head: bool = False,
              cond_headers: dict | None = None):
    """GET-miss proxying: when an object under a replication rule is not
    (yet) present locally, serve it from the first reachable remote
    target instead of returning 404 (reference
    proxyGetToReplicationTarget / proxyHeadToReplicationTarget,
    cmd/bucket-replication.go).

    Returns (target, response_headers, chunk_iter|None) or None.  Only
    unversioned requests proxy: replica versions carry fresh ids on this
    implementation's targets, so a source version id has no meaning
    remotely.
    """
    try:
        cfg = meta.replication_config(bucket)
    except Exception:
        return None
    if cfg is None or cfg.match(key) is None:
        return None
    # conditional headers are forwarded so the TARGET evaluates them
    # (304/412 pass back through); the pseudo-header ":status" carries
    # the remote status to the caller
    fwd = dict(cond_headers or {})
    if range_header:
        fwd["Range"] = range_header
    ok = (200, 206, 304, 412)
    for tgt in load_targets(meta, bucket):
        try:
            client = tgt.client()
            if head:
                rh = client.head_object(tgt.bucket, key,
                                        headers=fwd or None, ok=ok)
                chunks = None
            else:
                rh, chunks = client.get_object_stream(
                    tgt.bucket, key, headers=fwd or None, ok=ok,
                    with_headers=True)
            if stats is not None:
                stats.inc(proxied=1)
                stats.inc_target(tgt.arn, proxied=1)
            return tgt, rh, chunks
        except S3ClientError as e:
            # 404 = the object simply is not on this target; anything
            # else marks the target unhealthy
            if e.status != 404 and stats is not None:
                stats.target(tgt.arn).last_failure = time.time()
            continue
        except OSError:
            if stats is not None:
                stats.target(tgt.arn).last_failure = time.time()
            continue
    return None
