"""Compiled residual row engine: numpy batch programs for the row tier.

The interpreter in sql.Evaluator walks the AST per record; for the
residual tier (queries the native/columnar tiers decline, or builds
without the native library) that walk IS the scan cost — on narrow rows
even csv.reader alone costs more per byte than the letter target
allows.  This module compiles the residual plan once into numpy batch
programs executed over blocks: structural CSV parsing with
np.flatnonzero over the raw bytes, cell decoding through right/left-
aligned digit matrices, predicate masks from vectorized compares, and
projection gathers emitting row slices — the reference analogue is the
batch evaluator behind internal/s3select/sql/statement.go.

Exactness contract (the same shape as the native tier's ambiguity
replay, one level up): a block is vectorized only when every byte of it
provably evaluates exactly as the interpreter would — quote-free,
\r-free, column-regular CSV with clean integer cells; JSON LINES whose
referenced columns are type-uniform ints/floats/strings.  Any doubt
(odd cells, ragged rows, >2^53 integers, fractional SUMs whose pairwise
summation could differ in the last ulp) drops that block — or just the
doubtful rows — to the compiled-closure interpreter, so output stays
byte-identical to sql.Evaluator, errors included.

Disable with MINIO_TPU_SELECT_BATCH=0 (the differential tests do, to
keep the pure interpreter as the reference).
"""

from __future__ import annotations

import io
import os
import re
from typing import Iterator

import numpy as np

from . import eventstream as es
# shared with the native tier (same _Fallback class, so helper raises
# propagate correctly): request parsing, aggregate shapes/commit, and
# the header reader — one implementation, no drift between tiers
from .native import (_Fallback, _agg_shape, _alias_strip, _commit_agg,
                     _csv_opts, _read_header)
from .records import _decomp
from .sql import (Between, Bin, Col, Evaluator, InList, IsNull, Like,
                  Lit, Query, SQLError, Un, _num, compile_predicate,
                  compile_projection)

CHUNK = 4 << 20
FLUSH = 256 << 10
MAX_W = 32          # cells wider than this take the per-row path
BIG = float(1 << 53)

stats = {"batch": 0, "fallback": 0, "interp_blocks": 0, "bytes": 0}


def _enabled() -> bool:
    return os.environ.get("MINIO_TPU_SELECT_BATCH", "1") != "0"


class _InterpBlock(Exception):
    """Data shape doubt inside one block: that block replays through
    the compiled-closure interpreter (exactness preserved)."""


def _lit_ok(v) -> bool:
    if v is None or isinstance(v, bool):
        return False
    if isinstance(v, int) and abs(v) >= 2**53:
        return False
    return isinstance(v, (int, float, str))


_OPS = {"=": 0, "==": 0, "!=": 1, "<>": 1, "<": 2, "<=": 3, ">": 4,
        ">=": 5}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


def _apply_op(op: int, cmp3):
    """3-way compare array (-1/0/1) -> bool mask for op code."""
    if op == 0:
        return cmp3 == 0
    if op == 1:
        return cmp3 != 0
    if op == 2:
        return cmp3 < 0
    if op == 3:
        return cmp3 <= 0
    if op == 4:
        return cmp3 > 0
    return cmp3 >= 0


def _num_mask(op: int, vals, lit: float):
    if op == 0:
        return vals == lit
    if op == 1:
        return vals != lit
    if op == 2:
        return vals < lit
    if op == 3:
        return vals <= lit
    if op == 4:
        return vals > lit
    return vals >= lit


# ---------------------------------------------------------- CSV blocks


class _CsvBlock:
    """One quote-free, \r-free, column-regular CSV block parsed with
    numpy: separator positions via flatnonzero, per-column cell bounds
    via a gathered delimiter matrix, numeric/text cell views decoded
    through alignment matrices.  `bad` collects rows any leaf could not
    decide exactly; those re-evaluate through the interpreter."""

    def __init__(self, data: bytes, delim: int):
        self.data = data
        a = np.frombuffer(data, dtype=np.uint8)
        self.arr = a
        nl = np.flatnonzero(a == 10)
        rs = np.empty(len(nl), dtype=np.int64)
        if len(nl):
            rs[0] = 0
            rs[1:] = nl[:-1] + 1
        re_ = nl.astype(np.int64)
        keep = re_ > rs  # blank records: csv.reader skips them
        self.rs = rs[keep]
        self.re = re_[keep]
        self.n = len(self.rs)
        self.bad = np.zeros(self.n, dtype=bool)
        self._bounds: dict = {}
        self._nums: dict = {}
        self._ncols = -1
        dl = np.flatnonzero(a == delim)
        if self.n:
            di0 = np.searchsorted(dl, self.rs)
            di1 = np.searchsorted(dl, self.re)
            nd = di1 - di0
            if (nd != nd[0]).any():
                raise _InterpBlock("ragged rows")
            ndel = int(nd[0])
            self._ncols = ndel + 1
            self._D = (dl[di0[:, None] + np.arange(ndel)]
                       if ndel else np.empty((self.n, 0), dtype=np.int64))

    @property
    def ncols(self) -> int:
        return self._ncols

    def bounds(self, j: int):
        """(cell_start, cell_end) int64 arrays for column j, or None
        when the column does not exist in this block."""
        if self.n == 0 or j >= self._ncols:
            return None
        if j not in self._bounds:
            ndel = self._ncols - 1
            cs = self.rs if j == 0 else self._D[:, j - 1] + 1
            ce = self._D[:, j] if j < ndel else self.re
            self._bounds[j] = (cs, ce)
        return self._bounds[j]

    # 10^f is an exact float64 for f <= 22; a <= 15-digit mantissa is
    # exact in int64/float64, so fl(mantissa / 10^f) is the correctly-
    # rounded value of the decimal — bit-identical to Python's float()
    # (the Clinger/strtod fast path)
    _POW10F = 10.0 ** np.arange(16)

    def nums(self, j: int):
        """(float64 values, exact bool) for column j: clean
        [-]?digits[.digits] cells totalling <= 15 digits decode exactly
        through a right-aligned digit matrix (integer mantissa with the
        decimal point squeezed out, divided by an exact power of ten);
        everything else (exponents, text, empties, huge cells) is
        not-exact and takes the per-row path."""
        if j in self._nums:
            return self._nums[j]
        cs, ce = self.bounds(j)
        w = ce - cs
        a = self.arr
        neg = np.zeros(self.n, dtype=bool)
        has = w > 0
        idx0 = np.where(has, cs, 0)
        neg[has] = a[idx0[has]] == 45  # '-'
        ds = cs + neg  # first digit or '.'
        dw = ce - ds
        # up to 15 digits plus at most one '.'
        ok = has & (dw > 0) & (dw <= 16)
        okw = dw[ok]
        maxw = int(okw.max()) if len(okw) else 0
        vals = np.zeros(self.n, dtype=np.float64)
        if maxw:
            # right-aligned window: positions before the cell read as
            # '0' (so pad slots can never fake a '.')
            idx = ce[:, None] - maxw + np.arange(maxw)
            valid = idx >= ds[:, None]
            chars = a[np.clip(idx, 0, len(a) - 1)].astype(np.int64)
            chars[~valid] = 48
            isdot = chars == 46
            digits = np.where(isdot, 0, chars - 48)
            ok &= ~(((digits < 0) | (digits > 9)) & ~isdot).any(axis=1)
            ndots = isdot.sum(axis=1)
            ndigits = dw - ndots
            ok &= (ndots <= 1) & (ndigits > 0) & (ndigits <= 15)
            pow10 = (10 ** np.arange(maxw - 1, -1, -1)).astype(np.int64)
            base = digits @ pow10
            hasdot = ndots > 0
            if hasdot.any():
                # squeeze the '.' out of the mantissa: digits left of
                # the dot sit one slot too high in `base`, so subtract
                # their contribution and re-add it shifted down a place
                left = np.where(np.cumsum(isdot, axis=1) == 0,
                                digits, 0) @ pow10
                mant = np.where(hasdot, base - left + left // 10, base)
                frac = np.where(
                    hasdot & ok, maxw - 1 - isdot.argmax(axis=1), 0)
                vals = mant.astype(np.float64) / self._POW10F[frac]
            else:
                vals = base.astype(np.float64)
            vals[neg] = -vals[neg]
        self._nums[j] = (vals, ok)
        return self._nums[j]

    def texts(self, j: int):
        """(left-aligned char matrix padded with -1, exact bool): ASCII
        cells of <= MAX_W bytes; -1 padding makes shorter-is-less
        lexicographic compares match Python's."""
        key = ("t", j)
        if key in self._nums:
            return self._nums[key]
        cs, ce = self.bounds(j)
        w = ce - cs
        ok = w <= MAX_W
        maxw = MAX_W
        idx = cs[:, None] + np.arange(maxw)
        valid = idx < ce[:, None]
        chars = self.arr[np.clip(idx, 0, len(self.arr) - 1)].astype(
            np.int16)
        chars[~valid] = -1
        ok &= ~((chars >= 0x80) & valid).any(axis=1)  # non-ASCII: Python
        self._nums[key] = (chars, ok)
        return self._nums[key]

    def cell(self, j: int, i: int):
        b = self.bounds(j)
        if b is None:
            return None
        cs, ce = b
        return self.data[int(cs[i]):int(ce[i])].decode("utf-8",
                                                       "replace")


def _text_cmp3(chars, lit: bytes):
    """Row-wise 3-way lexicographic compare of the padded char matrix
    against a literal (padded the same way)."""
    lb = np.full(chars.shape[1], -1, dtype=np.int16)
    enc = np.frombuffer(lit, dtype=np.uint8).astype(np.int16)
    lb[:len(enc)] = enc
    diff = chars - lb[None, :]
    nz = diff != 0
    any_ = nz.any(axis=1)
    first = nz.argmax(axis=1)
    d = diff[np.arange(len(chars)), first]
    return np.where(any_, np.sign(d), 0)


class _CsvWhere:
    """WHERE AST -> fn(block) -> bool mask; rows any leaf marks bad are
    re-decided by the interpreter afterwards."""

    def __init__(self, where, resolve):
        self.fn = self._comp(where, resolve) if where is not None else None

    def _leaf_cmp(self, j, op: str, lit):
        opc = _OPS[op]
        nlit = _num(lit)
        is_num = isinstance(nlit, (int, float)) and not isinstance(
            nlit, bool)
        if is_num:
            flit = float(nlit)

            def leaf(blk):
                if blk.bounds(j) is None:
                    return np.zeros(blk.n, dtype=bool)
                vals, ok = blk.nums(j)
                m = _num_mask(opc, vals, flit)
                m &= ok
                blk.bad |= ~ok
                return m
            return leaf
        lb = str(lit).encode()
        if len(lb) > MAX_W:
            raise _Fallback("long literal")

        def leaf(blk):
            if blk.bounds(j) is None:
                return np.zeros(blk.n, dtype=bool)
            chars, ok = blk.texts(j)
            m = _apply_op(opc, _text_cmp3(chars, lb))
            m &= ok
            blk.bad |= ~ok
            return m
        return leaf

    def _leaf_like(self, j, pat: str, esc, negate: bool):
        # vectorize the four byte-anchorable shapes; other patterns
        # (embedded %/_, escapes) take the per-row path wholesale
        if esc is not None or "_" in pat:
            raise _Fallback("LIKE shape")
        body = pat.strip("%")
        if "%" in body or not body.isascii() or len(body) > MAX_W:
            raise _Fallback("LIKE shape")
        kind = ("eq" if "%" not in pat else
                "prefix" if pat == body + "%" else
                "suffix" if pat == "%" + body else
                "contains" if pat == "%" + body + "%" else None)
        if kind is None:
            raise _Fallback("LIKE shape")
        bb = body.encode()

        def leaf(blk):
            b = blk.bounds(j)
            if b is None:
                return np.zeros(blk.n, dtype=bool)
            cs, ce = b
            w = ce - cs
            chars, ok = blk.texts(j)
            n = len(bb)
            enc = np.frombuffer(bb, dtype=np.uint8).astype(np.int16)
            if kind == "eq":
                m = (w == n) & (chars[:, :max(n, 1)] ==
                                (enc[None, :] if n else -1)).all(axis=1) \
                    if n else (w == 0)
            elif kind == "prefix":
                m = (w >= n) & (chars[:, :n] == enc[None, :]).all(axis=1) \
                    if n else w >= 0
            elif kind == "contains":
                # %needle%: vectorized substring scan — one all-rows
                # window compare per shift (<= MAX_W of them).  The -1
                # padding can never match a needle byte (needles are
                # ASCII >= 0), so windows past a cell's end fail
                # without an explicit bound check.
                if n == 0:
                    m = w >= 0  # LIKE '%%' matches every cell
                else:
                    m = np.zeros(blk.n, dtype=bool)
                    for s in range(MAX_W - n + 1):
                        m |= (chars[:, s:s + n]
                              == enc[None, :]).all(axis=1)
            else:  # suffix: right-align via gather
                idx = ce[:, None] - n + np.arange(n)
                valid = idx >= cs[:, None]
                tailc = blk.arr[np.clip(idx, 0, len(blk.arr) - 1)].astype(
                    np.int16)
                tailc[~valid] = -1
                m = (w >= n) & (tailc == enc[None, :]).all(axis=1) \
                    if n else w >= 0
            m &= ok
            blk.bad |= ~ok
            # null is impossible here (column-regular block), so NOT
            # LIKE is a plain complement
            return ~m if negate else m
        return leaf

    def _comp(self, e, resolve):
        if isinstance(e, Un):
            if e.op != "not":
                raise _Fallback("unary " + e.op)
            inner = self._comp(e.e, resolve)
            return lambda blk: ~inner(blk)
        if isinstance(e, Bin) and e.op in ("and", "or"):
            lf, rf = self._comp(e.l, resolve), self._comp(e.r, resolve)
            if e.op == "and":
                return lambda blk: lf(blk) & rf(blk)
            return lambda blk: lf(blk) | rf(blk)
        if isinstance(e, Like):
            if not (isinstance(e.e, Col) and isinstance(e.pat, Lit)
                    and isinstance(e.pat.v, str)
                    and (e.esc is None or isinstance(e.esc, Lit))):
                raise _Fallback("LIKE shape")
            return self._leaf_like(
                resolve(e.e.name), e.pat.v,
                e.esc.v if e.esc is not None else None, e.negate)
        if isinstance(e, InList):
            if not (isinstance(e.e, Col) and all(
                    isinstance(x, Lit) and _lit_ok(x.v) for x in e.items)):
                raise _Fallback("IN shape")
            j = resolve(e.e.name)
            leaves = [self._leaf_cmp(j, "=", x.v) for x in e.items]
            negate = e.negate

            def leaf(blk):
                if blk.bounds(j) is None:
                    return np.zeros(blk.n, dtype=bool)  # NULL: 3VL
                m = leaves[0](blk)
                for lf in leaves[1:]:
                    m = m | lf(blk)
                return ~m if negate else m
            return leaf
        if isinstance(e, Between):
            if not (isinstance(e.e, Col) and isinstance(e.lo, Lit)
                    and _lit_ok(e.lo.v) and isinstance(e.hi, Lit)
                    and _lit_ok(e.hi.v)):
                raise _Fallback("BETWEEN shape")
            j = resolve(e.e.name)
            lo = self._leaf_cmp(j, ">=", e.lo.v)
            hi = self._leaf_cmp(j, "<=", e.hi.v)
            negate = e.negate

            def leaf(blk):
                if blk.bounds(j) is None:
                    return np.zeros(blk.n, dtype=bool)  # NULL: 3VL
                m = lo(blk) & hi(blk)
                return ~m if negate else m
            return leaf
        if isinstance(e, IsNull):
            if not isinstance(e.e, Col):
                raise _Fallback("IS NULL shape")
            j = resolve(e.e.name)
            negate = e.negate

            def leaf(blk):
                b = blk.bounds(j)
                if b is None:
                    m = np.ones(blk.n, dtype=bool)
                else:
                    cs, ce = b
                    m = ce == cs
                return ~m if negate else m
            return leaf
        if isinstance(e, Bin) and e.op in _OPS:
            def fold_neg(node):
                if isinstance(node, Un) and node.op == "neg" \
                        and isinstance(node.e, Lit) \
                        and isinstance(node.e.v, (int, float)) \
                        and not isinstance(node.e.v, bool):
                    return Lit(-node.e.v)
                return node

            col, lit, flip = e.l, fold_neg(e.r), False
            if isinstance(fold_neg(e.l), Lit):
                col, lit, flip = e.r, fold_neg(e.l), True
            if not (isinstance(col, Col) and isinstance(lit, Lit)
                    and _lit_ok(lit.v)):
                raise _Fallback("cmp shape")
            op = _FLIP.get(e.op, e.op) if flip else e.op
            return self._leaf_cmp(resolve(col.name), op, lit.v)
        raise _Fallback(f"unsupported node {type(e).__name__}")

    def mask(self, blk):
        if self.fn is None:
            return None
        return self.fn(blk)


# ------------------------------------------------------------- CSV tier


def _try_csv(req, query: Query, rw, object_size: int, out):
    delim, quote, header = _csv_opts(req)
    compression = req.input_ser.get("CompressionType", "NONE") or "NONE"
    aggs = _agg_shape(query)
    proj_cols: list | None = None
    emit = False
    if aggs is None:
        oc = req.output_ser.get("CSV")
        if "CSV" not in req.output_ser or not isinstance(
                oc, (dict, type(None))):
            raise _Fallback("output serialization")
        oc = oc if isinstance(oc, dict) else {}
        if (oc.get("FieldDelimiter", ",") or ",") != delim \
                or (oc.get("RecordDelimiter", "\n") or "\n") != "\n" \
                or (oc.get("QuoteCharacter", '"') or '"') != '"':
            raise _Fallback("output serialization")
        if query.star and not query.projections:
            emit = True
        elif query.projections and all(
                isinstance(p.expr, Col) for p in query.projections):
            names_out = [p.alias or Evaluator._auto_name(p.expr, i)
                         for i, p in enumerate(query.projections)]
            if len(set(names_out)) != len(names_out):
                raise _Fallback("duplicate projection names")
            proj_cols = [p.expr for p in query.projections]
            emit = True
        else:
            raise _Fallback("projection shape")

    raw = _decomp(rw, compression)
    if header in ("USE", "IGNORE"):
        hline, leftover = _read_header(raw, quote)
        try:
            names = [h.strip() for h in
                     hline.decode("utf-8", "replace").split(delim)] \
                if header == "USE" else []
        except Exception:
            raise _Fallback("header decode")
        if header == "USE" and hline.strip() == b"":
            names = []
    else:
        names, leftover = [], b""
    if names:
        lowered = [s.lower() for s in names]
        if len(set(names)) != len(names) or \
                len(set(lowered)) != len(lowered) or \
                any(re.fullmatch(r"_\d+", s) for s in names):
            raise _Fallback("ambiguous header names")

    def resolve(name: str) -> int:
        p = _alias_strip(name, query.table_alias)
        if names:
            if p in names:
                return names.index(p)
            lw = [s.lower() for s in names]
            if p.lower() in lw:
                return lw.index(p.lower())
        if re.fullmatch(r"_\d+", p):
            i = int(p[1:]) - 1
            if i >= 0 and (not names or i < len(names)):
                return i
        return 1 << 30  # unknown column: dict lookup yields None

    where = _CsvWhere(query.where, resolve)
    agg_cols = []
    if aggs is not None:
        for what, colname, fname in aggs:
            agg_cols.append(None if colname is None else resolve(colname))
    proj_resolved = [resolve(c.name) for c in proj_cols] \
        if proj_cols is not None else None

    ev = Evaluator(query)
    matches = compile_predicate(ev)
    project = compile_projection(ev)
    stats["batch"] += 1
    rw.commit()
    keys = [(names[i] if i < len(names) and names[i] else f"_{i + 1}")
            for i in range(len(names))]
    qb, db = quote.encode(), delim.encode()

    def rec_of(blk: _CsvBlock, i: int) -> dict:
        row = [blk.cell(j, i) for j in range(blk.ncols)]
        ks = keys if keys else []
        if len(row) > len(ks):
            ks = ks + [f"_{k + 1}" for k in range(len(ks), len(row))]
        return dict(zip(ks, row))

    def gen() -> Iterator[bytes]:
        returned = 0
        outbuf = bytearray()
        limit = query.limit
        n_out = 0
        tail = leftover
        keys_state = list(keys)

        def interp_block(block: bytes):
            nonlocal n_out
            import csv as csv_mod

            stats["interp_blocks"] += 1
            text = block.decode("utf-8", "replace")
            rdr = csv_mod.reader(io.StringIO(text), delimiter=delim,
                                 quotechar=quote)
            for row in rdr:
                if not row:
                    continue
                if len(row) > len(keys_state):
                    keys_state.extend(
                        f"_{k + 1}" for k in range(len(keys_state),
                                                   len(row)))
                rec = dict(zip(keys_state, row))
                if aggs is not None:
                    if matches(rec):
                        ev.accumulate(rec)
                    continue
                if not matches(rec):
                    continue
                if limit is not None and n_out >= limit:
                    return
                outbuf.extend(out.serialize(project(rec)))
                n_out += 1

        def vector_block(block: bytes):
            nonlocal n_out
            blk = _CsvBlock(block, ord(delim))
            if blk.n == 0:
                return
            mask = where.mask(blk)
            badidx = np.flatnonzero(blk.bad)
            if len(badidx) * 2 > blk.n:
                raise _InterpBlock("mostly non-vector cells")
            if len(badidx):
                if mask is None:
                    mask = np.ones(blk.n, dtype=bool)
                for i in badidx:
                    mask[i] = matches(rec_of(blk, int(i)))
            if aggs is not None:
                results = []
                for (what, colname, fname), j in zip(aggs, agg_cols):
                    if j is None:
                        results.append(
                            ("count",
                             int(mask.sum()) if mask is not None
                             else blk.n, 0.0, None, None))
                        continue
                    b = blk.bounds(j)
                    if b is None:
                        results.append((fname, 0, 0.0, None, None))
                        continue
                    cs, ce = b
                    sel = (ce > cs) if mask is None else mask & (ce > cs)
                    if what == 0:
                        results.append(("count", int(sel.sum()), 0.0,
                                        None, None))
                        continue
                    vals, ok = blk.nums(j)
                    if (~ok & sel).any():
                        # text/exponent/huge cells under the mask: SUM
                        # may raise, MIN/MAX mixes _cmp_pair — interp
                        raise _InterpBlock("non-numeric aggregate cells")
                    sv = vals[sel]
                    if what == 1:
                        # fractional values sum order-dependently (numpy
                        # pairwise vs the interpreter's sequential adds
                        # can differ in the last ulp); integer-valued
                        # floats below 2^53 are associative-exact
                        if len(sv) and (
                                (sv != np.floor(sv)).any()
                                or float(np.abs(sv).sum()) >= BIG):
                            raise _InterpBlock("sum exactness")
                        results.append((fname, int(sel.sum()),
                                        float(sv.sum()) if len(sv)
                                        else 0.0, None, None))
                    else:
                        if not len(sv):
                            results.append((fname, 0, 0.0, None, None))
                            continue
                        si = np.flatnonzero(sel)
                        lo = _num(blk.cell(j, int(si[int(sv.argmin())])))
                        hi = _num(blk.cell(j, int(si[int(sv.argmax())])))
                        results.append((fname, int(sel.sum()), 0.0,
                                        lo, hi))
                _commit_agg(ev, results)
                return
            # emit path: verbatim row slices / cell gathers
            sel = np.arange(blk.n) if mask is None else \
                np.flatnonzero(mask)
            for i in sel:
                if limit is not None and n_out >= limit:
                    return
                i = int(i)
                if proj_resolved is None:
                    outbuf.extend(block[int(blk.rs[i]):
                                        int(blk.re[i])])
                    outbuf.extend(b"\n")
                else:
                    cells = []
                    for j in proj_resolved:
                        b = blk.bounds(j)
                        cells.append(b"" if b is None else
                                     block[int(b[0][i]):int(b[1][i])])
                    outbuf.extend(db.join(cells))
                    outbuf.extend(b"\n")
                n_out += 1

        def interp_stream(prefix: bytes):
            """Quote byte seen: record boundaries are no longer plain
            newlines (a quoted field may span read blocks, and no
            block-local rule can place the split soundly — Python csv's
            in-quote state is sequential).  Hand the REST of the stream
            to one continuous csv.reader, exactly like the interpreter
            tier."""
            nonlocal n_out
            import csv as csv_mod

            stats["interp_blocks"] += 1

            class _Chain(io.RawIOBase):
                def __init__(self, head, rest):
                    self._head = io.BytesIO(head)
                    self._rest = rest

                def readable(self):
                    return True

                def readinto(self, b):
                    got = self._head.readinto(b)
                    if got:
                        return got
                    data = self._rest.read(len(b)) or b""
                    n = len(data)
                    b[:n] = data
                    return n

            text = io.TextIOWrapper(_Chain(prefix, raw),
                                    encoding="utf-8", errors="replace",
                                    newline="")
            rdr = csv_mod.reader(text, delimiter=delim, quotechar=quote)
            for row in rdr:
                if not row:
                    continue
                stats["bytes"] += sum(len(c) for c in row) + len(row)
                if len(row) > len(keys_state):
                    keys_state.extend(
                        f"_{k + 1}" for k in range(len(keys_state),
                                                   len(row)))
                rec = dict(zip(keys_state, row))
                if aggs is not None:
                    if matches(rec):
                        ev.accumulate(rec)
                    continue
                if not matches(rec):
                    continue
                if limit is not None and n_out >= limit:
                    return
                outbuf.extend(out.serialize(project(rec)))
                n_out += 1

        try:
            while True:
                data = raw.read(CHUNK)
                final = not data
                buf = tail + (data or b"")
                tail = b""
                if not buf:
                    break
                if qb in buf:
                    interp_stream(buf)
                    break
                if final:
                    block = buf
                else:
                    k = buf.rfind(b"\n")
                    if k < 0:
                        tail = buf
                        if len(tail) > (64 << 20):
                            raise SQLError("record too large")
                        continue
                    block, tail = buf[:k + 1], buf[k + 1:]
                stats["bytes"] += len(block)
                if block and not block.endswith(b"\n"):
                    block += b"\n"  # final record without newline
                try:
                    if b"\r" in block or (emit and b'"' in block):
                        # bare \r; for emit ALSO the OUTPUT quote char
                        # (a cell may contain '"' while the input quote
                        # differs): the writer would re-quote, so the
                        # interpreter serializes.  \r never splits a
                        # record across blocks (splits are at '\n'
                        # only), so per-block replay stays exact here.
                        raise _InterpBlock("\\r or output-quote block")
                    vector_block(block)
                except _InterpBlock:
                    interp_block(block)
                while len(outbuf) >= FLUSH:
                    returned += FLUSH
                    yield es.records_message(bytes(outbuf[:FLUSH]))
                    del outbuf[:FLUSH]
                if emit and limit is not None and n_out >= limit:
                    break
                if final:
                    break
            if aggs is not None:
                outbuf.extend(out.serialize(ev.aggregate_result()))
            if outbuf:
                returned += len(outbuf)
                yield es.records_message(bytes(outbuf))
            if req.request_progress:
                yield es.progress_message(object_size, object_size,
                                          returned)
            yield es.stats_message(object_size, object_size, returned)
            yield es.end_message()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))

    return gen()


# ------------------------------------------------------------ JSON tier


class _JsonBlock:
    """A batch of parsed JSON LINES documents with typed column
    caches."""

    def __init__(self, docs: list):
        self.docs = docs
        self.n = len(docs)
        self._cols: dict = {}

    def col(self, k: str) -> list:
        if k not in self._cols:
            self._cols[k] = [d.get(k) for d in self.docs]
        return self._cols[k]

    def types(self, k: str) -> set:
        key = ("t", k)
        if key not in self._cols:
            self._cols[key] = set(map(type, self.col(k)))
        return self._cols[key]

    def nums(self, k: str):
        """float64 values (None -> nan) for an int/float column; raises
        _InterpBlock on anything exactness can't survive."""
        key = ("n", k)
        if key not in self._cols:
            tps = self.types(k)
            if not tps <= {int, float, type(None)} or bool in tps:
                raise _InterpBlock("mixed types")
            try:
                vals = np.asarray(self.col(k), dtype=np.float64)
            except (OverflowError, ValueError, TypeError):
                raise _InterpBlock("unconvertible numbers")
            with np.errstate(invalid="ignore"):
                if (np.abs(vals) >= BIG).any():
                    raise _InterpBlock("big-int exactness")
            self._cols[key] = vals
        return self._cols[key]

    def nulls(self, k: str):
        key = ("0", k)
        if key not in self._cols:
            self._cols[key] = np.fromiter(
                (v is None for v in self.col(k)), dtype=bool,
                count=self.n)
        return self._cols[key]

    def strs(self, k: str):
        key = ("s", k)
        if key not in self._cols:
            tps = self.types(k)
            if not tps <= {str, type(None)}:
                raise _InterpBlock("mixed types")
            self._cols[key] = np.array(
                ["" if v is None else v for v in self.col(k)])
        return self._cols[key]


class _JsonWhere:
    def __init__(self, where, resolve):
        self.fn = self._comp(where, resolve) if where is not None else None

    def mask(self, blk):
        if self.fn is None:
            return None
        return self.fn(blk)

    def _leaf_cmp(self, k, op: str, lit):
        opc = _OPS[op]
        nlit = _num(lit)
        is_num = isinstance(nlit, (int, float)) and not isinstance(
            nlit, bool)

        def leaf(blk):
            tps = blk.types(k)
            if tps <= {int, float, type(None)} and bool not in tps:
                if not is_num:
                    # number cells vs text literal: str() renderings —
                    # the interpreter decides
                    raise _InterpBlock("number vs text literal")
                vals = blk.nums(k)
                with np.errstate(invalid="ignore"):
                    m = _num_mask(opc, vals, float(nlit))
                if opc == 1 and type(None) in tps:
                    m &= ~blk.nulls(k)  # null != lit is FALSE, not True
                return m
            if tps <= {str, type(None)}:
                sv = blk.strs(k)
                if is_num:
                    # numeric-string cells compare numerically: the
                    # interpreter's _cmp_pair semantics, per block
                    raise _InterpBlock("string vs numeric literal")
                m = _apply_op(
                    opc, np.sign(
                        (sv > str(lit)).astype(np.int8) -
                        (sv < str(lit)).astype(np.int8)))
                if type(None) in tps:
                    nz = blk.nulls(k)
                    m &= ~nz
                return m
            raise _InterpBlock("mixed types")
        return leaf

    def _valid(self, k):
        def leaf(blk):
            return ~blk.nulls(k)
        return leaf

    def _comp(self, e, resolve):
        if isinstance(e, Un):
            if e.op != "not":
                raise _Fallback("unary " + e.op)
            inner = self._comp(e.e, resolve)
            return lambda blk: ~inner(blk)
        if isinstance(e, Bin) and e.op in ("and", "or"):
            lf, rf = self._comp(e.l, resolve), self._comp(e.r, resolve)
            if e.op == "and":
                return lambda blk: lf(blk) & rf(blk)
            return lambda blk: lf(blk) | rf(blk)
        if isinstance(e, InList):
            if not (isinstance(e.e, Col) and all(
                    isinstance(x, Lit) and _lit_ok(x.v)
                    for x in e.items)):
                raise _Fallback("IN shape")
            k = resolve(e.e.name)
            leaves = [self._leaf_cmp(k, "=", x.v) for x in e.items]
            validf = self._valid(k)
            negate = e.negate

            def leaf(blk):
                m = leaves[0](blk)
                for lf in leaves[1:]:
                    m = m | lf(blk)
                return (validf(blk) & ~m) if negate else m
            return leaf
        if isinstance(e, Between):
            if not (isinstance(e.e, Col) and isinstance(e.lo, Lit)
                    and _lit_ok(e.lo.v) and isinstance(e.hi, Lit)
                    and _lit_ok(e.hi.v)):
                raise _Fallback("BETWEEN shape")
            k = resolve(e.e.name)
            lo = self._leaf_cmp(k, ">=", e.lo.v)
            hi = self._leaf_cmp(k, "<=", e.hi.v)
            validf = self._valid(k)
            negate = e.negate

            def leaf(blk):
                m = lo(blk) & hi(blk)
                return (validf(blk) & ~m) if negate else m
            return leaf
        if isinstance(e, IsNull):
            if not isinstance(e.e, Col):
                raise _Fallback("IS NULL shape")
            k = resolve(e.e.name)
            negate = e.negate

            def leaf(blk):
                tps = blk.types(k)
                m = blk.nulls(k).copy()
                if str in tps:
                    if not tps <= {str, type(None)}:
                        raise _InterpBlock("mixed types")
                    m |= blk.strs(k) == ""
                elif not tps <= {int, float, type(None)} \
                        or bool in tps:
                    raise _InterpBlock("mixed types")
                return ~m if negate else m
            return leaf
        if isinstance(e, Bin) and e.op in _OPS:
            def fold_neg(node):
                if isinstance(node, Un) and node.op == "neg" \
                        and isinstance(node.e, Lit) \
                        and isinstance(node.e.v, (int, float)) \
                        and not isinstance(node.e.v, bool):
                    return Lit(-node.e.v)
                return node

            col, lit, flip = e.l, fold_neg(e.r), False
            if isinstance(fold_neg(e.l), Lit):
                col, lit, flip = e.r, fold_neg(e.l), True
            if not (isinstance(col, Col) and isinstance(lit, Lit)
                    and _lit_ok(lit.v)):
                raise _Fallback("cmp shape")
            op = _FLIP.get(e.op, e.op) if flip else e.op
            return self._leaf_cmp(resolve(col.name), op, lit.v)
        raise _Fallback(f"unsupported node {type(e).__name__}")


def _try_json(req, query: Query, rw, object_size: int, out):
    j = req.input_ser["JSON"] if isinstance(req.input_ser["JSON"], dict) \
        else {}
    if (j.get("Type", "DOCUMENT") or "DOCUMENT").upper() != "LINES":
        raise _Fallback("JSON type")
    aggs = _agg_shape(query)
    if aggs is None:
        raise _Fallback("projection shape")
    compression = req.input_ser.get("CompressionType", "NONE") or "NONE"

    def resolve(name: str) -> str:
        return _alias_strip(name, query.table_alias)

    where = _JsonWhere(query.where, resolve)
    agg_keys = [None if colname is None else resolve(colname)
                for what, colname, fname in aggs]
    ev = Evaluator(query)
    matches = compile_predicate(ev)
    raw = _decomp(rw, compression)
    stats["batch"] += 1
    rw.commit()

    def gen() -> Iterator[bytes]:
        import json as json_mod

        returned = 0
        outbuf = bytearray()
        tail = ""
        dec = io.TextIOWrapper(_Reader(raw), encoding="utf-8",
                               errors="replace")

        def run_docs(docs: list) -> None:
            blk = _JsonBlock(docs)
            mask = where.mask(blk)
            results = []
            for (what, colname, fname), k in zip(aggs, agg_keys):
                if k is None:
                    results.append(
                        ("count",
                         int(mask.sum()) if mask is not None else blk.n,
                         0.0, None, None))
                    continue
                col = blk.col(k)
                tps = blk.types(k)
                present = ~blk.nulls(k)
                if str in tps:
                    if not tps <= {str, type(None)}:
                        raise _InterpBlock("mixed types")
                    if what == 0:
                        sel = present & (blk.strs(k) != "")
                        if mask is not None:
                            sel &= mask
                        results.append(("count", int(sel.sum()), 0.0,
                                        None, None))
                        continue
                    raise _InterpBlock("string aggregate cells")
                vals = blk.nums(k)  # raises _InterpBlock on mixes
                sel = present if mask is None else mask & present
                if what == 0:
                    results.append(("count", int(sel.sum()), 0.0,
                                    None, None))
                    continue
                sv = vals[sel]
                if what == 1:
                    if len(sv):
                        if (sv != np.floor(sv)).any() or \
                                float(np.abs(sv).sum()) >= BIG:
                            # fractional or huge sums: pairwise numpy
                            # addition may differ from the sequential
                            # interpreter in the last ulp
                            raise _InterpBlock("sum exactness")
                    results.append((fname, int(sel.sum()),
                                    float(sv.sum()) if len(sv) else 0.0,
                                    None, None))
                else:
                    if not len(sv):
                        results.append((fname, 0, 0.0, None, None))
                        continue
                    si = np.flatnonzero(sel)
                    lo = col[int(si[int(sv.argmin())])]
                    hi = col[int(si[int(sv.argmax())])]
                    results.append((fname, int(sel.sum()), 0.0, lo, hi))
            _commit_agg(ev, results)

        def interp_lines(lines: list) -> None:
            stats["interp_blocks"] += 1
            for line in lines:
                try:
                    doc = json_mod.loads(line)
                except ValueError as exc:
                    raise SQLError(f"invalid JSON line: {exc}")
                rec = doc if isinstance(doc, dict) else {"_1": doc}
                if matches(rec):
                    ev.accumulate(rec)

        try:
            while True:
                data = dec.read(CHUNK)
                final = not data
                text = tail + (data or "")
                tail = ""
                if not text:
                    break
                if not final:
                    k = text.rfind("\n")
                    if k < 0:
                        tail = text
                        if len(tail) > (64 << 20):
                            raise SQLError("record too large")
                        continue
                    text, tail = text[:k + 1], text[k + 1:]
                stats["bytes"] += len(text)
                lines = [ln for ln in
                         (s.strip() for s in text.split("\n")) if ln]
                if not lines:
                    if final:
                        break
                    continue
                docs = None
                try:
                    docs = json_mod.loads("[" + ",".join(lines) + "]")
                except ValueError:
                    interp_lines(lines)  # per-line: exact error order
                if docs is not None and len(docs) != len(lines):
                    # a malformed line containing a TOP-LEVEL comma
                    # ('{"a":2},{"a":3}') parses as extra array
                    # elements instead of raising — only a 1:1 line:doc
                    # mapping proves the join was faithful
                    docs = None
                    interp_lines(lines)
                if docs is not None:
                    try:
                        run_docs([d if isinstance(d, dict) else
                                  {"_1": d} for d in docs])
                    except _InterpBlock:
                        interp_lines(lines)
                if final:
                    break
            outbuf.extend(out.serialize(ev.aggregate_result()))
            returned += len(outbuf)
            yield es.records_message(bytes(outbuf))
            if req.request_progress:
                yield es.progress_message(object_size, object_size,
                                          returned)
            yield es.stats_message(object_size, object_size, returned)
            yield es.end_message()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))

    return gen()


class _Reader(io.RawIOBase):
    """Minimal adapter so TextIOWrapper accepts our byte streams."""

    def __init__(self, raw):
        self._raw = raw

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        ri = getattr(self._raw, "readinto", None)
        if ri is not None:
            try:
                return ri(b) or 0
            except (NotImplementedError, io.UnsupportedOperation):
                pass
        data = self._raw.read(len(b)) or b""
        n = len(data)
        b[:n] = data
        return n


# -------------------------------------------------------------- dispatch


def try_batch(req, query: Query, rw, object_size: int,
              out) -> Iterator[bytes] | None:
    """Probe + run the compiled row tier.  Returns the event-stream
    iterator, or None (with `rw` rewound) when the plain interpreter
    must take the query."""
    if not _enabled():
        rw.rewind()
        return None
    try:
        if "CSV" in req.input_ser:
            return _try_csv(req, query, rw, object_size, out)
        if "JSON" in req.input_ser:
            return _try_json(req, query, rw, object_size, out)
    except _Fallback:
        pass
    stats["fallback"] += 1
    rw.rewind()
    return None
