"""S3 Select: SQL over CSV/JSON objects.

Reference: internal/s3select/select.go:218 (S3Select.Open/Evaluate —
request XML unmarshalling, input/output serialization dispatch,
event-stream response).  `run_select` is the engine entry: it streams
records through the parsed query and yields event-stream messages.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Iterator

from . import eventstream as es
from .records import (CSVInput, CSVOutput, JSONInput, JSONOutput,
                      ParquetInput)
from .sql import Evaluator, SQLError, parse

# flush records to the client in ~256 KiB batches like the reference
# (maxRecordSize/bufferSize in internal/s3select)
FLUSH = 256 << 10

# residual-tier observability: queries that fell through every
# accelerated tier to the per-record interpreter, and the bytes they
# scanned (a row-tier query reads the whole object)
row_stats = {"queries": 0, "bytes": 0}


class SelectRequest:
    """Parsed SelectObjectContentRequest XML."""

    def __init__(self, expression: str, input_ser: dict, output_ser: dict,
                 request_progress: bool = False):
        self.expression = expression
        self.input_ser = input_ser
        self.output_ser = output_ser
        self.request_progress = request_progress

    @classmethod
    def from_xml(cls, body: bytes) -> "SelectRequest":
        try:
            root = ET.fromstring(body)
        except ET.ParseError as e:
            raise SQLError(f"malformed request XML: {e}")

        def strip(tag: str) -> str:
            return tag.rsplit("}", 1)[-1]

        def walk(el) -> dict:
            out = {}
            for ch in el:
                k = strip(ch.tag)
                out[k] = walk(ch) if len(ch) else (ch.text or "")
            return out

        doc = walk(root)
        expr = doc.get("Expression", "")
        etype = (doc.get("ExpressionType") or "SQL").upper()
        if etype != "SQL":
            raise SQLError(f"unsupported ExpressionType {etype}")
        if not expr:
            raise SQLError("missing Expression")
        inp = doc.get("InputSerialization")
        out = doc.get("OutputSerialization")
        if not isinstance(inp, dict) or not isinstance(out, dict):
            raise SQLError("missing Input/OutputSerialization")
        progress = False
        rp = doc.get("RequestProgress")
        if isinstance(rp, dict):
            progress = str(rp.get("Enabled", "")).lower() == "true"
        return cls(expr, inp, out, progress)


def _make_input(req: SelectRequest, stream):
    inp = req.input_ser
    compression = inp.get("CompressionType", "NONE") or "NONE"
    if "CSV" in inp:
        c = inp["CSV"] if isinstance(inp["CSV"], dict) else {}
        return CSVInput(
            stream,
            header_info=c.get("FileHeaderInfo", "USE") or "USE",
            delimiter=c.get("FieldDelimiter", ",") or ",",
            quote=c.get("QuoteCharacter", '"') or '"',
            record_delimiter=c.get("RecordDelimiter", "\n") or "\n",
            compression=compression,
            comment=c.get("Comments", "") or "",
        )
    if "JSON" in inp:
        j = inp["JSON"] if isinstance(inp["JSON"], dict) else {}
        return JSONInput(stream, json_type=j.get("Type", "DOCUMENT"),
                         compression=compression)
    if "Parquet" in inp:
        return ParquetInput(stream, compression=compression)
    raise SQLError("InputSerialization requires CSV or JSON")


def _make_output(req: SelectRequest):
    out = req.output_ser
    if "JSON" in out:
        j = out["JSON"] if isinstance(out["JSON"], dict) else {}
        return JSONOutput(record_delimiter=j.get("RecordDelimiter", "\n")
                          or "\n")
    c = out.get("CSV")
    c = c if isinstance(c, dict) else {}
    return CSVOutput(
        delimiter=c.get("FieldDelimiter", ",") or ",",
        record_delimiter=c.get("RecordDelimiter", "\n") or "\n",
        quote=c.get("QuoteCharacter", '"') or '"',
    )


def run_select(req: SelectRequest, stream,
               object_size: int) -> Iterator[bytes]:
    """Yield event-stream messages for the query over `stream`.

    SQL/evaluation errors BEFORE the first byte is sent surface as
    SQLError (the handler maps them to an HTTP 4xx); failures after
    streaming has begun become an error event in-band, which is the
    only option the framing leaves (reference behaves the same)."""
    query = parse(req.expression)
    # constructing the Evaluator validates the projection shape (mixed
    # aggregate/scalar raises) BEFORE any bytes stream — HTTP 4xx, not
    # an in-band error
    Evaluator(query)
    out = _make_output(req)

    # tiered engine (fastest first, each falling through when the
    # query/data shape is out of its scope):
    #  1. native C++ block scan (csrc/select_scan.cpp — the simdjson/
    #     simd-CSV analogue, internal/s3select/simdj/reader.go:27)
    #  2. pyarrow columnar (vectorized masks over arrow batches)
    #  3. compiled row programs (select/batch.py — numpy batch
    #     evaluation of residual plans, interpreter per doubtful block)
    #  4. the per-record interpreter below (full SQL surface)
    from . import batch, columnar, native

    rw = columnar.Rewindable(stream)
    fast = native.try_native(req, query, rw, object_size, out)
    if fast is not None:
        yield from fast
        return
    fast = columnar.try_columnar(req, query, rw, object_size, out)
    if fast is not None:
        yield from fast
        return
    fast = batch.try_batch(req, query, rw, object_size, out)
    if fast is not None:
        yield from fast
        return
    # fallback: replay the probed prefix, then stream WITHOUT recording —
    # the row engine must not accumulate the whole object in memory
    row_stats["queries"] += 1
    row_stats["bytes"] += object_size
    rw.stop_recording()
    reader = _make_input(req, rw)
    yield from row_engine_stream(reader, query, out, object_size,
                                 req.request_progress)


def row_engine_stream(reader, query, out, object_size: int,
                      request_progress: bool) -> Iterator[bytes]:
    """The row engine proper: records from `reader` through compiled
    predicate/projection closures into event-stream messages.  Shared
    by run_select's fallback tier and the columnar module's post-spool
    Parquet fallback."""
    ev = Evaluator(query)
    returned = 0
    buf = bytearray()
    try:
        # one-time closure compilation of the predicate/projection —
        # the row engine's per-record cost is these two calls
        from .sql import compile_predicate, compile_projection

        matches = compile_predicate(ev)
        project = compile_projection(ev)
        limit = query.limit
        n_out = 0
        for rec in reader:
            if ev.is_aggregate:
                if matches(rec):
                    ev.accumulate(rec)
                continue
            if not matches(rec):
                continue
            if limit is not None and n_out >= limit:
                break
            buf += out.serialize(project(rec))
            n_out += 1
            if len(buf) >= FLUSH:
                returned += len(buf)
                yield es.records_message(bytes(buf))
                buf.clear()
            if limit is not None and n_out >= limit:
                break
        if ev.is_aggregate:
            buf += out.serialize(ev.aggregate_result())
        if buf:
            returned += len(buf)
            yield es.records_message(bytes(buf))
        if request_progress:
            yield es.progress_message(object_size, object_size, returned)
        yield es.stats_message(object_size, object_size, returned)
        yield es.end_message()
    except SQLError as e:
        yield es.error_message("InvalidQuery", str(e))
