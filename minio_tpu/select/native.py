"""Native (C++) S3 Select fast path: block-streamed CSV/NDJSON scans.

The reference accelerates Select with simdjson and a generated-assembly
CSV scanner (internal/s3select/simdj/reader.go:27,
select_benchmark_test.go); this is the equivalent here — csrc/
select_scan.cpp tokenizes blocks, evaluates predicate leaves, and folds
aggregates at C speed, while this driver composes leaf masks with
numpy, keeps the cross-block aggregate state, and REPLAYS any block the
kernels flag as ambiguous through the row engine (sql.Evaluator), so
semantics match the row engine bit-for-bit even on garbage data
(whitespace-padded numbers, >2^53 ints, escaped quotes, JSON escapes,
invalid JSON lines...).

Scope (everything else falls through to the pyarrow columnar path, then
the row engine):
- CSV (single-char delim/quote, "\\n" records, no comments) or JSON
  Type=LINES; any CompressionType (blocks are read post-decompression)
- aggregate-only projections (COUNT/SUM/MIN/MAX/AVG over a column or
  COUNT(*)), or CSV `SELECT *` whose output serialization is a byte-
  passthrough of the input (same delimiter, "\\n" records, CSV output)
- WHERE: AND/OR/NOT over `col <op> literal`, LIKE, IN, BETWEEN,
  IS [NOT] NULL — the same leaf language as the columnar path

Disable with MINIO_TPU_SELECT_NATIVE=0 (MINIO_TPU_SELECT_COLUMNAR=0
disables this path too — it gates everything above the row engine).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Iterator

import numpy as np

from . import eventstream as es
from .records import _decomp
from .sql import (AGGREGATES, Between, Bin, Cast, Col, Evaluator, Func,
                  InList, IsNull, Like, Lit, Query, SQLError, Un,
                  _cmp_pair, _num)

CHUNK = 4 << 20
FLUSH = 256 << 10
PAD = 8  # kernel SWAR parsers read up to 8 bytes past a cell

stats = {"native": 0, "fallback": 0, "replay_blocks": 0,
         # per-tier observability: bytes the native kernels consumed and
         # the subset re-decided by the Python replay (the residual-
         # replay fraction gauge in server/metrics.py is their ratio)
         "bytes_scanned": 0, "bytes_replayed": 0}

_OPS = {"=": 0, "==": 0, "!=": 1, "<>": 1, "<": 2, "<=": 3, ">": 4,
        ">=": 5}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
# scalar functions the C kernels evaluate per cell (csrc FN_* codes);
# non-ASCII cells flag ambiguous and replay, preserving exactness
_FN_CODES = {"lower": 1, "upper": 2, "trim": 3, "ltrim": 4, "rtrim": 5,
             "char_length": 6, "length": 6, "character_length": 6}
_FN_SUBSTR = 7

_CSRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "csrc")
# MINIO_TPU_NATIVE_LIB points the loader at an alternate build of the
# host library — the sanitizer harness uses it to swap in the
# asan/ubsan/tsan variants (csrc/Makefile `make asan` etc.)
_LIBPATH = os.environ.get("MINIO_TPU_NATIVE_LIB") or os.path.join(
    _CSRC, "libminio_tpu_host.so")
_lock = threading.Lock()
_lib = None
_lib_tried = False

_i64 = ctypes.c_int64
_dbl = ctypes.c_double
_vp = ctypes.c_void_p
_cp = ctypes.c_char_p


def _load():
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        try:
            lib = ctypes.CDLL(_LIBPATH)
        except OSError:
            return None
        lib.sel_csv_scan.restype = _i64
        lib.sel_csv_scan.argtypes = [
            _vp, _i64, ctypes.c_char, ctypes.c_char, ctypes.c_int, _vp,
            ctypes.c_int32, _i64, _vp, _vp, _vp, ctypes.POINTER(_i64)]
        lib.sel_cmp_num.restype = _i64
        lib.sel_cmp_num.argtypes = [
            _vp, _vp, _vp, _i64, ctypes.c_int, _dbl, _cp, ctypes.c_int32,
            _vp, ctypes.c_int, ctypes.c_int32, ctypes.c_int32]
        lib.sel_cmp_str.restype = _i64
        lib.sel_cmp_str.argtypes = [
            _vp, _vp, _vp, _i64, ctypes.c_int, _cp, ctypes.c_int32, _vp,
            ctypes.c_int, ctypes.c_int32, ctypes.c_int32]
        lib.sel_like.restype = _i64
        lib.sel_like.argtypes = [
            _vp, _vp, _vp, _i64, _cp, ctypes.c_int32, _cp, _vp,
            ctypes.c_int, ctypes.c_int32, ctypes.c_int32]
        lib.sel_cmp_expr.restype = _i64
        lib.sel_cmp_expr.argtypes = [
            _vp, _vp, _vp, _i64, ctypes.c_int, _dbl, _vp, _vp,
            ctypes.c_int, _vp]
        lib.sel_json_cmp_expr.restype = _i64
        lib.sel_json_cmp_expr.argtypes = [
            _vp, _vp, _vp, _vp, _i64, ctypes.c_int, _dbl, _vp, _vp,
            ctypes.c_int, _vp]
        lib.sel_valid.argtypes = [_vp, _i64, _vp]
        lib.sel_isnull.argtypes = [_vp, _i64, _vp]
        lib.sel_agg.restype = _i64
        lib.sel_agg.argtypes = [
            _vp, _vp, _vp, _i64, _vp, ctypes.c_int, ctypes.POINTER(_dbl),
            ctypes.POINTER(_dbl), ctypes.POINTER(_dbl),
            ctypes.POINTER(_i64), ctypes.POINTER(_i64),
            ctypes.POINTER(_i64)]
        lib.sel_emit_rows.restype = _i64
        lib.sel_emit_rows.argtypes = [
            _vp, _vp, _i64, _vp, _i64, _vp, ctypes.POINTER(_i64)]
        lib.sel_emit_cols.restype = _i64
        lib.sel_emit_cols.argtypes = [
            _vp, _vp, _vp, _i64, _vp, ctypes.c_int32, _i64, _vp, _i64,
            ctypes.c_char, _vp, ctypes.POINTER(_i64)]
        lib.sel_json_scan.restype = _i64
        lib.sel_json_scan.argtypes = [
            _vp, _i64, ctypes.c_int, _vp, _vp, ctypes.c_int32, _i64, _vp,
            _vp, _vp, _vp, _vp, ctypes.POINTER(_i64)]
        lib.sel_json_cmp.restype = _i64
        lib.sel_json_cmp.argtypes = [
            _vp, _vp, _vp, _vp, _i64, ctypes.c_int, _dbl, ctypes.c_int,
            _cp, ctypes.c_int32, _vp, ctypes.c_int, ctypes.c_int32,
            ctypes.c_int32]
        lib.sel_json_like.restype = _i64
        lib.sel_json_like.argtypes = [
            _vp, _vp, _vp, _vp, _i64, _cp, ctypes.c_int32, _cp, _vp,
            ctypes.c_int, ctypes.c_int32, ctypes.c_int32]
        lib.sel_json_valid.argtypes = [_vp, _i64, _vp]
        lib.sel_json_isnull.restype = _i64
        lib.sel_json_isnull.argtypes = [_vp, _vp, _i64, _vp]
        lib.sel_json_agg.restype = _i64
        lib.sel_json_agg.argtypes = [
            _vp, _vp, _vp, _vp, _i64, _vp, ctypes.c_int,
            ctypes.POINTER(_dbl), ctypes.POINTER(_dbl),
            ctypes.POINTER(_dbl), ctypes.POINTER(_i64),
            ctypes.POINTER(_i64), ctypes.POINTER(_i64)]
        # fused one-pass kernels (absent from pre-refactor .so builds:
        # the driver then stays on the multi-pass array path)
        try:
            lib.sel_csv_agg_fused.restype = _i64
            lib.sel_csv_agg_fused.argtypes = [
                _vp, _i64, ctypes.c_char, ctypes.c_char, ctypes.c_int,
                _vp, ctypes.c_int32,
                ctypes.c_int32, _vp, _vp, _vp, _vp, _vp, _vp, _vp, _vp,
                _vp, _cp, _cp, _vp, ctypes.c_int32, _vp, _vp,
                ctypes.c_int32, _vp, _vp, _vp, _vp, _vp, _vp, _vp, _vp,
                _vp, _vp,
                ctypes.POINTER(_i64), ctypes.POINTER(_i64),
                ctypes.POINTER(_i64), ctypes.POINTER(_i64)]
            lib.sel_json_agg_fused.restype = _i64
            lib.sel_json_agg_fused.argtypes = [
                _vp, _i64, ctypes.c_int, _vp, _vp, ctypes.c_int32,
                ctypes.c_int32, _vp, _vp, _vp, _vp, _vp, _vp, _vp, _vp,
                _vp, _vp, _cp, _cp, _vp, ctypes.c_int32, _vp, _vp,
                ctypes.c_int32, _vp, _vp, _vp, _vp, _vp, _vp, _vp, _vp,
                _vp, _vp,
                ctypes.POINTER(_i64), ctypes.POINTER(_i64),
                ctypes.POINTER(_i64)]
            lib.has_fused = True
        except AttributeError:
            lib.has_fused = False
        _lib = lib
        return _lib


def _enabled() -> bool:
    return (os.environ.get("MINIO_TPU_SELECT_NATIVE", "1") != "0"
            and os.environ.get("MINIO_TPU_SELECT_COLUMNAR", "1") != "0")


class _Fallback(Exception):
    pass


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(_vp)


# ------------------------------------------------------------ WHERE plan


def _lit_num(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and (not isinstance(v, int) or abs(v) < 2**53))


def _lit_ok(v) -> bool:
    if v is None:
        return False
    if isinstance(v, bool):
        return False  # bool literals: row-engine coercions, stay off
    if isinstance(v, int) and abs(v) >= 2**53:
        return False
    return True


def _like_plan(pat: str, esc: str | None) -> tuple[bytes, bytes]:
    """SQL LIKE pattern -> (bytes, literal-mask) for the C matcher:
    mask byte 1 = literal, 0 = wildcard role for '%'/'_'."""
    out = bytearray()
    lit = bytearray()
    i = 0
    while i < len(pat):
        c = pat[i]
        if esc and c == esc and i + 1 < len(pat):
            for b in pat[i + 1].encode():
                out.append(b)
                lit.append(1)
            i += 2
            continue
        for b in c.encode():
            out.append(b)
            lit.append(0 if c in "%_" else 1)
        i += 1
    return bytes(out), bytes(lit)


class _Plan:
    """Compiled WHERE tree: leaves call C kernels over (starts, lens[,
    types]) arrays; interior nodes compose numpy bool arrays.  `amb`
    accumulates the kernels' ambiguous-cell counts for the current
    block — nonzero means the Python replay must decide the block."""

    # Alongside the per-leaf closures, _comp records a flat "fused
    # program" (leaf descriptor rows + a postfix combiner) that the
    # one-pass kernels execute per row DURING the structural scan —
    # every leaf shape _comp accepts is expressible, so f_ok only goes
    # False on size limits (kernel fixed stacks).
    F_MAX_LEAVES = 64

    def __init__(self, where, resolve, is_json: bool):
        self.is_json = is_json
        self.cols: list = []          # resolved column ids, plan order
        self._col_of: dict = {}
        self.amb = 0
        self.f_leaves: list = []      # (kind, slot, op, isnum, fn, fa,
        #                                fb, num, aux, auxmask, expr)
        self.f_prog: list = []        # postfix: >=0 push leaf; -1 AND,
        #                                -2 OR, -3 NOT
        self.f_ok = True
        self.fn = self._comp(where, resolve) if where is not None else None

    def _f_leaf(self, kind, slot, op=0, isnum=0, fn=0, fa=0, fb=0,
                num=0.0, aux=b"", auxmask=None, expr=None) -> None:
        if not self.f_ok:
            return
        if len(self.f_leaves) >= self.F_MAX_LEAVES:
            self.f_ok = False
            return
        self.f_leaves.append((kind, slot, op, isnum, fn, fa, fb, float(num),
                              aux, auxmask, expr))
        self.f_prog.append(len(self.f_leaves) - 1)

    def _f_op(self, code: int) -> None:
        self.f_prog.append(code)

    def pack_fused(self, slot_map) -> dict | None:
        """-> ctypes-ready fused-program arrays, with plan slots
        remapped through slot_map (plan-col -> captured-cell index), or
        None when the program exceeds the kernel's fixed bounds."""
        if not self.f_ok:
            return None
        n = len(self.f_leaves)
        blob = bytearray()
        mask = bytearray()
        ecodes: list[int] = []
        eops: list[float] = []
        kind = np.zeros(n, dtype=np.int32)
        slot = np.zeros(n, dtype=np.int32)
        op = np.zeros(n, dtype=np.int32)
        isnum = np.zeros(n, dtype=np.int32)
        fn = np.zeros(n, dtype=np.int32)
        fa = np.zeros(n, dtype=np.int32)
        fb = np.zeros(n, dtype=np.int32)
        num = np.zeros(n, dtype=np.float64)
        aoff = np.zeros(n, dtype=np.int32)
        alen = np.zeros(n, dtype=np.int32)
        for i, (k, sl, o, inum, f, a, b, nv, aux, auxmask, expr) in \
                enumerate(self.f_leaves):
            kind[i] = k
            slot[i] = slot_map[sl]
            op[i] = o
            isnum[i] = inum
            fn[i] = f
            fa[i] = a
            fb[i] = b
            num[i] = nv
            if expr is not None:
                aoff[i] = len(ecodes)
                alen[i] = len(expr[0])
                ecodes.extend(expr[0])
                eops.extend(expr[1])
            else:
                aoff[i] = len(blob)
                alen[i] = len(aux)
                blob += aux
                mask += auxmask if auxmask is not None else b"\0" * len(aux)
        prog = np.array(self.f_prog, dtype=np.int32) if self.f_prog \
            else np.zeros(1, dtype=np.int32)
        return {
            "nleaves": n, "kind": kind, "slot": slot, "op": op,
            "isnum": isnum, "fn": fn, "fa": fa, "fb": fb, "num": num,
            "aoff": aoff, "alen": alen, "blob": bytes(blob),
            "mask": bytes(mask), "prog": prog,
            "prog_len": len(self.f_prog),
            "ecodes": np.array(ecodes or [0], dtype=np.int32),
            "eops": np.array(eops or [0.0], dtype=np.float64),
        }

    def _slot(self, resolved) -> int:
        if resolved not in self._col_of:
            self._col_of[resolved] = len(self.cols)
            self.cols.append(resolved)
        return self._col_of[resolved]

    def mask(self, ctx) -> np.ndarray | None:
        self.amb = 0
        if self.fn is None:
            return None
        return self.fn(ctx)

    # ctx: object with .buf (ctypes buffer), .starts/.lens/.types lists
    # of per-slot numpy arrays (length nrows), .n
    def _leaf_cmp(self, slot: int, op: str, lit_v, fn: int = 0,
                  fa: int = 0, fb: int = 0):
        lib = _load()
        opc = _OPS[op]
        numlit = _num(lit_v)
        strlit = str(lit_v).encode()
        is_num = isinstance(numlit, (int, float)) \
            and not isinstance(numlit, bool)
        if self.is_json:
            self._f_leaf(0, slot, opc, isnum=int(is_num),
                         num=float(numlit) if is_num else 0.0,
                         fn=fn, fa=fa, fb=fb, aux=strlit)
        elif is_num:
            self._f_leaf(0, slot, opc, num=float(numlit), fn=fn, fa=fa,
                         fb=fb, aux=strlit)
        else:
            self._f_leaf(1, slot, opc, fn=fn, fa=fa, fb=fb, aux=strlit)
        if self.is_json:
            def leaf(ctx):
                m = np.empty(ctx.n, dtype=np.uint8)
                self.amb += lib.sel_json_cmp(
                    ctx.buf, _ptr(ctx.starts[slot]), _ptr(ctx.lens[slot]),
                    _ptr(ctx.types[slot]), ctx.n, opc,
                    float(numlit) if is_num else 0.0, int(is_num),
                    strlit, len(strlit), _ptr(m), fn, fa, fb)
                return m.view(bool)
            return leaf
        if is_num:
            def leaf(ctx):
                m = np.empty(ctx.n, dtype=np.uint8)
                self.amb += lib.sel_cmp_num(
                    ctx.buf, _ptr(ctx.starts[slot]), _ptr(ctx.lens[slot]),
                    ctx.n, opc, float(numlit), strlit, len(strlit),
                    _ptr(m), fn, fa, fb)
                return m.view(bool)
            return leaf

        def leaf(ctx):
            m = np.empty(ctx.n, dtype=np.uint8)
            self.amb += lib.sel_cmp_str(
                ctx.buf, _ptr(ctx.starts[slot]), _ptr(ctx.lens[slot]),
                ctx.n, opc, strlit, len(strlit), _ptr(m), fn, fa, fb)
            return m.view(bool)
        return leaf

    def _num_prog(self, e):
        """Arithmetic/CAST chain over ONE column -> (Col, [(code,
        operand)]); _Fallback for anything else.  codes match csrc
        run_prog.  Literal operands must be clean numbers."""
        def walk(node):
            if isinstance(node, Col):
                return node, []
            if isinstance(node, Un) and node.op == "neg":
                col, prog = walk(node.e)
                return col, prog + [(5, 0.0)]  # 0 - x
            if isinstance(node, Cast):
                col, prog = walk(node.e)
                if node.typ in ("int", "integer"):
                    return col, prog + [(7, 0.0)]
                if node.typ in ("float", "decimal", "numeric", "double"):
                    return col, prog + [(8, 0.0)]
                raise _Fallback(f"CAST {node.typ}")
            if isinstance(node, Bin) and node.op in "+-*/%":
                code_l = {"+": 0, "-": 1, "*": 2, "/": 3, "%": 4}
                if isinstance(node.r, Lit) and _lit_num(node.r.v):
                    col, prog = walk(node.l)
                    return col, prog + [(code_l[node.op],
                                         float(_num(node.r.v)))]
                if isinstance(node.l, Lit) and _lit_num(node.l.v):
                    col, prog = walk(node.r)
                    if node.op == "+":
                        return col, prog + [(0, float(_num(node.l.v)))]
                    if node.op == "*":
                        return col, prog + [(2, float(_num(node.l.v)))]
                    if node.op == "-":
                        return col, prog + [(5, float(_num(node.l.v)))]
                    if node.op == "/":
                        return col, prog + [(6, float(_num(node.l.v)))]
                    raise _Fallback("lit % expr")
            raise _Fallback(f"expr shape {type(node).__name__}")

        col, prog = walk(e)
        if not prog:
            raise _Fallback("bare column")  # plain cmp path handles it
        return col, prog

    def _leaf_expr(self, e, resolve, op: str, lit_v):
        """expr(col) <op> numeric-literal leaf via sel_cmp_expr."""
        numlit = _num(lit_v)
        if not _lit_num(numlit):
            raise _Fallback("expr vs text literal")  # str() rendering
        col, prog = self._num_prog(e)
        slot = self._slot(resolve(col.name))
        lib = _load()
        opc = _OPS[op]
        codes = np.array([c for c, _ in prog], dtype=np.int32)
        ops = np.array([o for _, o in prog], dtype=np.float64)
        self._f_leaf(5, slot, opc, num=float(numlit),
                     expr=([c for c, _ in prog], [o for _, o in prog]))
        isj = self.is_json

        def leaf(ctx, slot=slot, codes=codes, ops=ops):
            m = np.empty(ctx.n, dtype=np.uint8)
            if isj:
                self.amb += lib.sel_json_cmp_expr(
                    ctx.buf, _ptr(ctx.starts[slot]), _ptr(ctx.lens[slot]),
                    _ptr(ctx.types[slot]), ctx.n, opc, float(numlit),
                    _ptr(codes), _ptr(ops), len(prog), _ptr(m))
            else:
                self.amb += lib.sel_cmp_expr(
                    ctx.buf, _ptr(ctx.starts[slot]), _ptr(ctx.lens[slot]),
                    ctx.n, opc, float(numlit), _ptr(codes), _ptr(ops),
                    len(prog), _ptr(m))
            return m.view(bool)
        return leaf

    def _col_fn(self, e, resolve):
        """Col or fn(Col[, args]) -> (slot, fn_code, fn_a, fn_b);
        _Fallback otherwise."""
        if isinstance(e, Col):
            return self._slot(resolve(e.name)), 0, 0, 0
        if isinstance(e, Func) and e.name in _FN_CODES \
                and len(e.args) == 1 and isinstance(e.args[0], Col):
            return (self._slot(resolve(e.args[0].name)),
                    _FN_CODES[e.name], 0, 0)
        if isinstance(e, Func) and e.name == "substring" \
                and 2 <= len(e.args) <= 3 \
                and isinstance(e.args[0], Col) \
                and all(isinstance(a, Lit) and isinstance(a.v, int)
                        and not isinstance(a.v, bool)
                        and abs(a.v) < 2**31 for a in e.args[1:]):
            start = int(e.args[1].v)
            if len(e.args) > 2:
                ln = int(e.args[2].v)
                if ln < 0:
                    # explicit negative lengths have Python-slice
                    # semantics in the row engine; -1 is also the
                    # internal 'to end' sentinel — never conflate them
                    raise _Fallback("negative SUBSTRING length")
            else:
                ln = -1  # sentinel: slice to end
            return (self._slot(resolve(e.args[0].name)), _FN_SUBSTR,
                    start, ln)
        raise _Fallback(f"unsupported operand {type(e).__name__}")

    def _valid(self, slot: int):
        lib = _load()
        if self.is_json:
            def v(ctx):
                m = np.empty(ctx.n, dtype=np.uint8)
                lib.sel_json_valid(_ptr(ctx.types[slot]), ctx.n, _ptr(m))
                return m.view(bool)
            return v

        def v(ctx):
            m = np.empty(ctx.n, dtype=np.uint8)
            lib.sel_valid(_ptr(ctx.lens[slot]), ctx.n, _ptr(m))
            return m.view(bool)
        return v

    def _comp(self, e, resolve):
        lib = _load()
        if isinstance(e, Un):
            if e.op != "not":
                raise _Fallback("unary " + e.op)
            inner = self._comp(e.e, resolve)
            self._f_op(-3)
            return lambda ctx: ~inner(ctx)
        if isinstance(e, Bin) and e.op in ("and", "or"):
            lf, rf = self._comp(e.l, resolve), self._comp(e.r, resolve)
            self._f_op(-1 if e.op == "and" else -2)
            if e.op == "and":
                return lambda ctx: lf(ctx) & rf(ctx)
            return lambda ctx: lf(ctx) | rf(ctx)
        if isinstance(e, Like):
            if not (isinstance(e.pat, Lit)
                    and isinstance(e.pat.v, str)
                    and (e.esc is None or (isinstance(e.esc, Lit)
                                           and isinstance(e.esc.v, str)))):
                raise _Fallback("LIKE shape")
            slot, fncode, fa, fb = self._col_fn(e.e, resolve)
            if fncode == _FN_CODES["char_length"]:
                raise _Fallback("LIKE over CHAR_LENGTH")
            pat, litmask = _like_plan(
                str(e.pat.v), str(e.esc.v) if e.esc is not None else None)
            negate = e.negate
            validf = self._valid(slot)
            self._f_leaf(2, slot, fn=fncode, fa=fa, fb=fb, aux=pat,
                         auxmask=litmask)
            if negate:
                # null cells make LIKE and NOT LIKE both false
                self._f_op(-3)
                self._f_leaf(4, slot)
                self._f_op(-1)
            fn = lib.sel_json_like if self.is_json else lib.sel_like

            def leaf(ctx, slot=slot, pat=pat, litmask=litmask,
                     negate=negate, fn=fn, fncode=fncode, fa=fa, fb=fb):
                m = np.empty(ctx.n, dtype=np.uint8)
                if self.is_json:
                    self.amb += fn(ctx.buf, _ptr(ctx.starts[slot]),
                                   _ptr(ctx.lens[slot]),
                                   _ptr(ctx.types[slot]), ctx.n,
                                   pat, len(pat), litmask, _ptr(m),
                                   fncode, fa, fb)
                else:
                    self.amb += fn(ctx.buf, _ptr(ctx.starts[slot]),
                                   _ptr(ctx.lens[slot]), ctx.n,
                                   pat, len(pat), litmask, _ptr(m),
                                   fncode, fa, fb)
                mb = m.view(bool)
                # null cells make LIKE and NOT LIKE both false
                return (validf(ctx) & ~mb) if negate else mb
            return leaf
        if isinstance(e, InList):
            if not all(isinstance(x, Lit) and _lit_ok(x.v)
                       for x in e.items):
                raise _Fallback("IN shape")
            slot, fncode, fa, fb = self._col_fn(e.e, resolve)
            leaves = [self._leaf_cmp(slot, "=", x.v, fncode, fa, fb)
                      for x in e.items]
            for _ in e.items[1:]:
                self._f_op(-2)
            validf = self._valid(slot)
            negate = e.negate
            if negate:
                self._f_op(-3)
                self._f_leaf(4, slot)
                self._f_op(-1)

            def leaf(ctx, leaves=leaves, negate=negate):
                m = leaves[0](ctx)
                for lf in leaves[1:]:
                    m = m | lf(ctx)
                return (validf(ctx) & ~m) if negate else m
            return leaf
        if isinstance(e, Between):
            if not (isinstance(e.lo, Lit) and _lit_ok(e.lo.v)
                    and isinstance(e.hi, Lit) and _lit_ok(e.hi.v)):
                raise _Fallback("BETWEEN shape")
            slot, fncode, fa, fb = self._col_fn(e.e, resolve)
            lo = self._leaf_cmp(slot, ">=", e.lo.v, fncode, fa, fb)
            hi = self._leaf_cmp(slot, "<=", e.hi.v, fncode, fa, fb)
            self._f_op(-1)
            validf = self._valid(slot)
            negate = e.negate
            if negate:
                self._f_op(-3)
                self._f_leaf(4, slot)
                self._f_op(-1)

            def leaf(ctx, lo=lo, hi=hi, negate=negate):
                m = lo(ctx) & hi(ctx)
                return (validf(ctx) & ~m) if negate else m
            return leaf
        if isinstance(e, IsNull):
            if not isinstance(e.e, Col):
                raise _Fallback("IS NULL shape")
            slot = self._slot(resolve(e.e.name))
            negate = e.negate
            isj = self.is_json
            self._f_leaf(3, slot)
            if negate:
                self._f_op(-3)

            def leaf(ctx, slot=slot, negate=negate):
                m = np.empty(ctx.n, dtype=np.uint8)
                if isj:
                    self.amb += lib.sel_json_isnull(
                        _ptr(ctx.lens[slot]), _ptr(ctx.types[slot]),
                        ctx.n, _ptr(m))
                else:
                    lib.sel_isnull(_ptr(ctx.lens[slot]), ctx.n, _ptr(m))
                mb = m.view(bool)
                return ~mb if negate else mb
            return leaf
        if isinstance(e, Bin) and e.op in ("=", "==", "!=", "<>", "<",
                                           "<=", ">", ">="):
            def fold_neg(node):
                # the parser renders -900 as Un(neg, Lit(900))
                if isinstance(node, Un) and node.op == "neg" \
                        and isinstance(node.e, Lit) \
                        and isinstance(node.e.v, (int, float)) \
                        and not isinstance(node.e.v, bool):
                    return Lit(-node.e.v)
                return node

            col, lit, flip = e.l, fold_neg(e.r), False
            if isinstance(fold_neg(e.l), Lit):
                col, lit, flip = e.r, fold_neg(e.l), True
            if not (isinstance(lit, Lit) and _lit_ok(lit.v)):
                raise _Fallback("cmp shape")
            op = _FLIP.get(e.op, e.op) if flip else e.op
            try:
                slot, fn, fa, fb = self._col_fn(col, resolve)
            except _Fallback:
                # arithmetic / CAST chain over one column
                return self._leaf_expr(col, resolve, op, lit.v)
            return self._leaf_cmp(slot, op, lit.v, fn, fa, fb)
        raise _Fallback(f"unsupported node {type(e).__name__}")


# --------------------------------------------------------------- shapes


def _agg_shape(q: Query):
    """-> list of (what, colname|None, func) or None.  what: 0 COUNT,
    1 SUM/AVG, 2 MIN/MAX."""
    if q.star or not q.projections:
        return None
    out = []
    for p in q.projections:
        f = p.expr
        if not (isinstance(f, Func) and f.name in AGGREGATES):
            return None
        if f.star:
            out.append((0, None, f.name))
            continue
        if len(f.args) != 1 or not isinstance(f.args[0], Col):
            return None
        what = 0 if f.name == "count" else (
            1 if f.name in ("sum", "avg") else 2)
        out.append((what, f.args[0].name, f.name))
    return out


def _alias_strip(name: str, alias: str) -> str:
    parts = name.split(".")
    if alias and parts and parts[0].lower() == alias:
        parts = parts[1:]
    if len(parts) != 1:
        raise _Fallback(f"nested column {name}")
    return parts[0]


class _Ctx:
    pass


class _Blocks:
    """Block feeder for the scan generators.

    Arena mode: stream bytes are readinto() a reusable padded bytearray
    (ONE copy — the old read()-then-stage path made two, and at fused-
    scan rates each extra memory pass costs as much as the scan
    itself).  Direct mode (fused aggregate queries over uncompressed
    memory-resident sources): segments of the source buffer go to the
    kernels zero-copy; a record crossing a segment boundary is simply
    re-scanned from its start (consumed semantics), and the final
    segment always goes through the arena so the kernels' 8-byte SWAR
    overread stays inside owned memory.
    """

    SEG = 16 << 20

    def __init__(self, raw, rw, leftover: bytes, compression: str,
                 direct_ok: bool):
        self.raw = raw
        self.tail = leftover or b""
        self.ba = bytearray(CHUNK + (1 << 20) + PAD)
        self.base = (ctypes.c_char * len(self.ba)).from_buffer(self.ba)
        self.dnp = None
        self.dpos = 0
        self._direct_blk = False
        self._blen = 0
        if direct_ok and (compression or "NONE").upper() in ("NONE", "") \
                and raw is rw:
            mv = rw.direct_buffer()
            if mv is not None and len(mv) > 0:
                self._mv = mv  # keeps the source export alive
                self.dnp = np.frombuffer(mv, dtype=np.uint8)

    def _grow(self, blen: int) -> None:
        if blen + PAD > len(self.ba):
            self.base = None
            self.ba = bytearray(blen * 2 + PAD)
            self.base = (ctypes.c_char * len(self.ba)).from_buffer(
                self.ba)

    def _stage(self, data: bytes, final: bool):
        if len(data) > (64 << 20):
            raise SQLError("record too large")
        blen = len(data)
        self._grow(blen)
        self.ba[:blen] = data
        self.ba[blen:blen + PAD] = b"\0" * PAD
        self.tail = b""
        self._direct_blk = False
        self._blen = blen
        return (ctypes.addressof(self.base), blen, final)

    def _find_nl(self, pos: int) -> int:
        d = self.dnp
        w = 1 << 16
        while True:
            end = min(pos + w, len(d))
            hits = np.flatnonzero(d[pos:end] == 10)
            if len(hits):
                return pos + int(hits[0])
            if end >= len(d):
                return -1
            w *= 16

    def next(self):
        """-> (base_address, block_len, final) or None at end."""
        d = self.dnp
        if d is not None:
            L = len(d)
            pos = self.dpos
            if pos >= L:
                self.dnp = None
                if self.tail:
                    return self._stage(self.tail, True)
                return None
            if self.tail:
                # stitch: complete the pending partial record with
                # bytes up to (and including) the next newline
                nl = self._find_nl(pos)
                if nl < 0:
                    self.dnp = None
                    self.dpos = L
                    return self._stage(
                        self.tail + d[pos:].tobytes(), True)
                data = self.tail + d[pos:nl + 1].tobytes()
                self.dpos = nl + 1
                return self._stage(data, False)
            rem = L - pos
            if rem > (1 << 16):
                # direct segment; always leave a staged tail so the
                # kernels' SWAR overread stays inside owned memory
                seg = min(self.SEG, rem - 4096)
                self._direct_blk = True
                self._blen = seg
                return (self.dnp.ctypes.data + pos, seg, False)
            self.dnp = None
            self.dpos = L
            return self._stage(d[pos:].tobytes(), True)
        # arena mode
        tlen = len(self.tail)
        self._grow(tlen + CHUNK)
        if tlen:
            self.ba[:tlen] = self.tail
            self.tail = b""
        got = self.raw.readinto(
            memoryview(self.ba)[tlen:tlen + CHUNK]) or 0
        blen = tlen + got
        if blen == 0:
            return None
        self.ba[blen:blen + PAD] = b"\0" * PAD
        self._direct_blk = False
        self._blen = blen
        return (ctypes.addressof(self.base), blen, got == 0)

    def view(self, off: int = 0):
        """Buffer view of the current block from `off` (for replay)."""
        if self._direct_blk:
            return self.dnp[self.dpos + off:self.dpos + self._blen]
        return memoryview(self.ba)[off:]

    def find(self, needle: bytes, a: int, b: int) -> int:
        """byte search within the current block (arena blocks only —
        direct blocks exist only on fused paths, which detect quotes
        in-kernel)."""
        if self._direct_blk:
            return -1
        return self.ba.find(needle, a, b)

    def advance(self, off: int) -> None:
        """Consume `off` bytes of the current block; the rest becomes
        the pending tail for the next one."""
        if self._direct_blk:
            if off == 0:
                # record longer than a direct segment: fall back to
                # stitched arena staging for this record
                self.tail = self.dnp[
                    self.dpos:self.dpos + self._blen].tobytes()
                self.dpos += self._blen
            else:
                self.dpos += off
            return
        blen = self._blen
        if off < blen:
            self.tail = bytes(self.ba[off:blen])
            if len(self.tail) > (64 << 20):
                raise SQLError("record too large")


# ------------------------------------------------------------- CSV path


def _csv_opts(req):
    inp = req.input_ser
    c = inp["CSV"] if isinstance(inp["CSV"], dict) else {}
    delim = c.get("FieldDelimiter", ",") or ","
    quote = c.get("QuoteCharacter", '"') or '"'
    header = (c.get("FileHeaderInfo", "USE") or "USE").upper()
    if (c.get("RecordDelimiter", "\n") or "\n") != "\n":
        raise _Fallback("record delimiter")
    if len(delim) != 1 or len(quote) != 1 or delim == quote:
        raise _Fallback("delim/quote")
    if c.get("Comments"):
        raise _Fallback("comments")
    return delim, quote, header


def _read_header(raw, quote: str) -> tuple[bytes, bytes]:
    """-> (header_line_without_newline, leftover buffered bytes).
    Falls back when the first line contains the quote char (quoted or
    multi-line headers: rare, pyarrow handles them)."""
    buf = b""
    while b"\n" not in buf:
        chunk = raw.read(65536)
        if not chunk:
            break
        buf += chunk
        if len(buf) > (1 << 20):
            raise _Fallback("header line too long")
    if b"\n" not in buf:
        return buf, b""
    line, rest = buf.split(b"\n", 1)
    if quote.encode() in line:
        raise _Fallback("quoted header")
    return line, rest


def _try_csv(req, query: Query, rw, object_size: int, out):
    delim, quote, header = _csv_opts(req)
    compression = req.input_ser.get("CompressionType", "NONE") or "NONE"
    aggs = _agg_shape(query)
    emit = False
    proj_cols_ast: list | None = None
    if aggs is None:
        # SELECT * passthrough, or plain-column projections, both with
        # CSV output whose serialization matches the input (cells copy
        # verbatim; quoted/\r blocks replay through the row engine)
        o = req.output_ser
        oc = o.get("CSV")
        if not isinstance(oc, (dict, type(None))) or "CSV" not in o:
            raise _Fallback("output serialization")
        oc = oc if isinstance(oc, dict) else {}
        if (oc.get("FieldDelimiter", ",") or ",") != delim \
                or (oc.get("RecordDelimiter", "\n") or "\n") != "\n" \
                or (oc.get("QuoteCharacter", '"') or '"') != '"':
            raise _Fallback("output serialization")
        if query.star and not query.projections:
            emit = True
        elif query.projections and all(
                isinstance(p.expr, Col) for p in query.projections):
            # the row engine projects into a DICT: duplicate output
            # names collapse to one column — fall back for that shape
            names_out = [p.alias or Evaluator._auto_name(p.expr, i)
                         for i, p in enumerate(query.projections)]
            if len(set(names_out)) != len(names_out):
                raise _Fallback("duplicate projection names")
            proj_cols_ast = [p.expr for p in query.projections]
            emit = True
        else:
            raise _Fallback("projection shape")

    raw = _decomp(rw, compression)
    if header == "USE":
        hline, leftover = _read_header(raw, quote)
        try:
            names = [h.strip() for h in
                     hline.decode("utf-8", "replace").split(delim)]
        except Exception:
            raise _Fallback("header decode")
        if hline.strip() == b"":
            names = []
    elif header == "IGNORE":
        hline, leftover = _read_header(raw, quote)
        names = []
    else:
        names = []
        leftover = b""

    def resolve(name: str) -> int:
        import re as re_mod

        p = _alias_strip(name, query.table_alias)
        if header == "USE" and names:
            if p in names:
                return names.index(p)
            lowered = [s.lower() for s in names]
            if p.lower() in lowered:
                return lowered.index(p.lower())
        if re_mod.fullmatch(r"_\d+", p):
            i = int(p[1:]) - 1
            if i >= 0 and (not names or i < len(names)):
                return i
        raise _Fallback(f"unknown column {name}")

    plan = _Plan(query.where, resolve, is_json=False)
    agg_cols: list[int | None] = []
    if aggs is not None:
        for what, colname, fname in aggs:
            agg_cols.append(None if colname is None
                            else resolve(colname))
    proj_resolved: list[int] = []
    if proj_cols_ast is not None:
        proj_resolved = [resolve(c.name) for c in proj_cols_ast]

    # needed columns, ascending, plus slot remap
    needed = sorted(set(plan.cols) | {c for c in agg_cols
                                      if c is not None}
                    | set(proj_resolved)) or [0]
    col_pos = {c: i for i, c in enumerate(needed)}
    ev = Evaluator(query)
    lib = _load()
    if lib is None:
        raise _Fallback("native lib unavailable")
    stats["native"] += 1
    rw.commit()
    keys = [(names[i] if names and i < len(names) and names[i]
             else f"_{i + 1}") for i in range(len(names))] if names else []

    # fused one-pass program: aggregate queries whose WHERE compiled and
    # whose working set fits the kernel's fixed cell registers run scan
    # + predicate + fold in a single traversal (quote-free blocks only —
    # a quoted block falls back to the multi-pass array kernels below)
    fused = None
    f_aggs = None
    if aggs is not None and getattr(lib, "has_fused", False) \
            and len(needed) <= 16:
        fused = plan.pack_fused([col_pos[c] for c in plan.cols])
        if fused is not None:
            f_aggs = {
                "what": np.array([w for w, _, _ in aggs],
                                 dtype=np.int32),
                "slot": np.array([-1 if c is None else col_pos[c]
                                  for c in agg_cols], dtype=np.int32),
            }

    def replay_rows(block: bytes, a: int, b: int, collect=None) -> None:
        """Row-engine evaluation of block[a:b] (complete records)."""
        import csv as csv_mod
        import io as io_mod

        stats["replay_blocks"] += 1
        stats["bytes_replayed"] += b - a
        text = bytes(block[a:b]).decode("utf-8", "replace")
        rdr = csv_mod.reader(io_mod.StringIO(text), delimiter=delim,
                             quotechar=quote)
        for rowvals in rdr:
            if not rowvals:
                continue
            if keys:
                rec = {}
                for i, v in enumerate(rowvals):
                    kk = keys[i] if i < len(keys) else f"_{i + 1}"
                    rec[kk] = v
            else:
                rec = {f"_{i + 1}": v for i, v in enumerate(rowvals)}
            if collect is not None:
                if ev.matches(rec):
                    collect(rec)
            elif ev.matches(rec):
                ev.accumulate(rec)

    def emit_collect(rec, sink, limiter):
        # replayed rows re-serialize through the row-engine writer so
        # quoted cells round-trip exactly as the slow path would
        if limiter[0] is not None and limiter[1] >= limiter[0]:
            return
        sink += out.serialize(ev.project(rec))
        limiter[1] += 1

    def gen() -> Iterator[bytes]:
        max_rows = 1 << 19
        col_arr = np.array(needed, dtype=np.int32)
        slots_arr = np.array([col_pos[c] for c in proj_resolved],
                             dtype=np.int32)
        # capacity math: a cell's bytes are emitted ONCE PER SLOT that
        # references its column (SELECT a AS x, a AS y re-emits a), so
        # the bound scales by the max per-column multiplicity
        from collections import Counter

        emit_mult = max(Counter(proj_resolved).values(), default=1)
        starts = np.empty((len(needed), max_rows), dtype=np.int32)
        lens = np.empty((len(needed), max_rows), dtype=np.int32)
        row_start = np.empty(max_rows + 1, dtype=np.int32)
        consumed = _i64()
        out_len = _i64()
        naggs = len(aggs) if aggs is not None else 0
        agg_cnt = np.zeros(naggs, dtype=np.int64)
        agg_s = np.zeros(naggs, dtype=np.float64)
        agg_mn = np.zeros(naggs, dtype=np.float64)
        agg_mx = np.zeros(naggs, dtype=np.float64)
        agg_mnp = np.zeros(naggs, dtype=np.int32)
        agg_mnl = np.zeros(naggs, dtype=np.int32)
        agg_mxp = np.zeros(naggs, dtype=np.int32)
        agg_mxl = np.zeros(naggs, dtype=np.int32)
        rows_o = _i64()
        amb_o = _i64()
        emit_buf = ctypes.create_string_buffer(CHUNK + (1 << 16)) \
            if emit else None
        saw_q = _i64()
        returned = 0
        outbuf = bytearray()
        limit = query.limit
        n_out = 0
        qb = quote.encode()
        # emit verbatim only when no cell could force the row-engine
        # writer to quote: input quote char, OUTPUT quote char (they
        # can differ — a cell may contain '"' while the input quote is
        # "'"), or a bare \r
        emit_guards = {qb, b'"', b"\r"}
        feeder = _Blocks(raw, rw, leftover, compression,
                         direct_ok=fused is not None)
        skip_fused = False  # quoted stretch pending: array path decides
        try:
            while True:
                blk = feeder.next()
                if blk is None:
                    break
                addr, blen, final = blk
                if emit and limit is not None and n_out >= limit:
                    break
                off = 0
                while off < blen:
                    seg_len = blen - off
                    pad = feeder.view(off)
                    cbuf = _vp(addr + off)
                    if fused is not None and not skip_fused:
                        lib.sel_csv_agg_fused(
                            cbuf, seg_len, delim.encode(), qb,
                            1 if final else 0, _ptr(col_arr),
                            len(needed), fused["nleaves"],
                            _ptr(fused["kind"]), _ptr(fused["slot"]),
                            _ptr(fused["op"]), _ptr(fused["fn"]),
                            _ptr(fused["fa"]), _ptr(fused["fb"]),
                            _ptr(fused["num"]), _ptr(fused["aoff"]),
                            _ptr(fused["alen"]), fused["blob"],
                            fused["mask"], _ptr(fused["prog"]),
                            fused["prog_len"], _ptr(fused["ecodes"]),
                            _ptr(fused["eops"]), naggs,
                            _ptr(f_aggs["what"]), _ptr(f_aggs["slot"]),
                            _ptr(agg_cnt), _ptr(agg_s), _ptr(agg_mn),
                            _ptr(agg_mx), _ptr(agg_mnp), _ptr(agg_mnl),
                            _ptr(agg_mxp), _ptr(agg_mxl),
                            ctypes.byref(rows_o), ctypes.byref(amb_o),
                            ctypes.byref(consumed), ctypes.byref(saw_q))
                        cons = int(consumed.value)
                        stats["bytes_scanned"] += cons
                        if amb_o.value > 0:
                            replay_rows(pad, 0, cons)
                        else:
                            results = []
                            for ai, (what, colname, fname) in \
                                    enumerate(aggs):
                                if agg_cols[ai] is None:
                                    results.append(
                                        ("count", int(agg_cnt[ai]), 0.0,
                                         None, None))
                                    continue
                                lo = hi = None
                                if what == 2 and int(agg_mnl[ai]) >= 0:
                                    a0 = int(agg_mnp[ai])
                                    l0 = int(agg_mnl[ai])
                                    lo = _num(bytes(pad[a0:a0 + l0])
                                              .decode("utf-8", "replace"))
                                    a1 = int(agg_mxp[ai])
                                    l1 = int(agg_mxl[ai])
                                    hi = _num(bytes(pad[a1:a1 + l1])
                                              .decode("utf-8", "replace"))
                                results.append((fname, int(agg_cnt[ai]),
                                                float(agg_s[ai]), lo, hi))
                            _commit_agg(ev, results)
                        off += cons
                        if int(saw_q.value):
                            skip_fused = True
                            continue
                        if cons == 0:
                            break
                        continue
                    n = lib.sel_csv_scan(
                        cbuf, seg_len, delim.encode(), quote.encode(),
                        1 if final else 0, _ptr(col_arr), len(needed),
                        max_rows, _ptr(starts), _ptr(lens),
                        _ptr(row_start), ctypes.byref(consumed))
                    skip_fused = False  # quoted stretch now consumed
                    if n == -2:
                        # unterminated quote at EOF: Python's csv module
                        # yields the open field as-is — replay exactly
                        if emit:
                            lim = [limit, n_out]
                            replay_rows(pad, 0, seg_len,
                                        collect=lambda rec: emit_collect(
                                            rec, outbuf, lim))
                            n_out = lim[1]
                        else:
                            replay_rows(pad, 0, seg_len)
                        stats["bytes_scanned"] += seg_len
                        off = blen
                        break
                    if n == 0:
                        break  # need more data
                    n = int(n)
                    ctx = _Ctx()
                    ctx.buf = cbuf
                    ctx.n = n
                    ctx.starts = [starts[col_pos[c], :n]
                                  for c in plan.cols]
                    ctx.lens = [lens[col_pos[c], :n] for c in plan.cols]
                    mask = plan.mask(ctx)
                    ambiguous = plan.amb > 0
                    if not ambiguous and aggs is not None:
                        # run every aggregate kernel BEFORE committing
                        # any state: a later kernel may turn up amb
                        results = []
                        kmask = None
                        if mask is not None:
                            kmask = np.ascontiguousarray(
                                mask.astype(np.uint8))
                        for (what, colname, fname), rcol in zip(
                                aggs, agg_cols):
                            if rcol is None:
                                results.append(
                                    ("count",
                                     int(mask.sum()) if mask is not None
                                     else n, 0.0, None, None))
                                continue
                            s = _dbl()
                            mn = _dbl()
                            mx = _dbl()
                            am = _i64()
                            ax = _i64()
                            ab = _i64()
                            sl = col_pos[rcol]
                            cnt = lib.sel_agg(
                                cbuf, _ptr(starts[sl, :n]),
                                _ptr(lens[sl, :n]), n,
                                _ptr(kmask) if kmask is not None
                                else None,
                                what, ctypes.byref(s), ctypes.byref(mn),
                                ctypes.byref(mx), ctypes.byref(am),
                                ctypes.byref(ax), ctypes.byref(ab))
                            if ab.value > 0:
                                ambiguous = True
                                break
                            lo = hi = None
                            if what == 2 and am.value >= 0:
                                a0 = int(starts[sl, am.value])
                                l0 = int(lens[sl, am.value])
                                lo = _num(bytes(pad[a0:a0 + l0]).decode(
                                    "utf-8", "replace"))
                                a1 = int(starts[sl, ax.value])
                                l1 = int(lens[sl, ax.value])
                                hi = _num(bytes(pad[a1:a1 + l1]).decode(
                                    "utf-8", "replace"))
                            results.append((fname, int(cnt),
                                            float(s.value), lo, hi))
                        if not ambiguous:
                            _commit_agg(ev, results)
                    if emit and not ambiguous and any(
                            feeder.find(g, off,
                                        off + int(consumed.value)) >= 0
                            for g in emit_guards):
                        # quoted cells (input OR output quote char),
                        # or bare \r, don't round-trip verbatim: the
                        # row-engine writer re-quotes — replay this
                        # batch through it
                        ambiguous = True
                    if ambiguous:
                        if emit:
                            lim = [limit, n_out]
                            replay_rows(pad, 0, int(consumed.value),
                                        collect=lambda rec: emit_collect(
                                            rec, outbuf, lim))
                            n_out = lim[1]
                        else:
                            replay_rows(pad, 0, int(consumed.value))
                    elif emit:
                        km = None
                        if mask is not None:
                            km = np.ascontiguousarray(
                                mask.astype(np.uint8))
                        lim = -1 if limit is None else max(
                            0, limit - n_out)
                        # emitted bytes bound: every cell emits once
                        # per slot referencing its column, plus per-row
                        # separators/newline
                        need_cap = int(consumed.value) * emit_mult + \
                            1 + n * (len(proj_resolved) + 2)
                        if need_cap > ctypes.sizeof(emit_buf):
                            emit_buf = ctypes.create_string_buffer(
                                need_cap * 2)
                        if proj_cols_ast is None:
                            wrote = lib.sel_emit_rows(
                                cbuf, _ptr(row_start[:n + 1]), n,
                                _ptr(km) if km is not None else None,
                                lim, emit_buf, ctypes.byref(out_len))
                        else:
                            wrote = lib.sel_emit_cols(
                                cbuf, _ptr(starts), _ptr(lens),
                                max_rows, _ptr(slots_arr),
                                len(proj_resolved), n,
                                _ptr(km) if km is not None else None,
                                lim, delim.encode(), emit_buf,
                                ctypes.byref(out_len))
                        n_out += int(wrote)
                        if out_len.value:
                            outbuf += emit_buf.raw[:out_len.value]
                            while len(outbuf) >= FLUSH:
                                returned += FLUSH
                                yield es.records_message(
                                    bytes(outbuf[:FLUSH]))
                                del outbuf[:FLUSH]
                        if limit is not None and n_out >= limit:
                            break
                    stats["bytes_scanned"] += int(consumed.value)
                    off += int(consumed.value)
                    if int(consumed.value) == 0:
                        break
                feeder.advance(off)
                if final:
                    break
            if aggs is not None:
                outbuf += out.serialize(ev.aggregate_result())
            if outbuf:
                returned += len(outbuf)
                yield es.records_message(bytes(outbuf))
            if req.request_progress:
                yield es.progress_message(object_size, object_size,
                                          returned)
            yield es.stats_message(object_size, object_size, returned)
            yield es.end_message()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))

    return gen()


def _commit_agg(ev: Evaluator, results) -> None:
    for i, (fname, cnt, s, lo, hi) in enumerate(results):
        st = ev._agg_state[i]
        st["count"] += cnt
        if fname in ("sum", "avg"):
            st["sum"] += s
        if fname in ("min", "max") and lo is not None:
            if st["min"] is None:
                st["min"], st["max"] = lo, hi
            else:
                a, b = _cmp_pair(lo, st["min"])
                if a < b:
                    st["min"] = lo
                a, b = _cmp_pair(hi, st["max"])
                if a > b:
                    st["max"] = hi


# ------------------------------------------------------------ JSON path


def _try_json(req, query: Query, rw, object_size: int, out):
    j = req.input_ser["JSON"] if isinstance(req.input_ser["JSON"], dict) \
        else {}
    if (j.get("Type", "DOCUMENT") or "DOCUMENT").upper() != "LINES":
        raise _Fallback("JSON type")
    aggs = _agg_shape(query)
    if aggs is None:
        raise _Fallback("projection shape")  # pyarrow handles these
    compression = req.input_ser.get("CompressionType", "NONE") or "NONE"
    raw = _decomp(rw, compression)

    keymap: dict[str, int] = {}

    def resolve(name: str) -> str:
        p = _alias_strip(name, query.table_alias)
        return p

    plan = _Plan(query.where, resolve, is_json=True)
    agg_keys: list[str | None] = []
    for what, colname, fname in aggs:
        agg_keys.append(None if colname is None
                        else resolve(colname))
    all_keys = list(dict.fromkeys(
        [k for k in plan.cols] + [k for k in agg_keys if k is not None]))
    if not all_keys:
        all_keys = ["\x00none"]  # dummy slot: bad-line detection only
    for i, k in enumerate(all_keys):
        keymap[k] = i
    ev = Evaluator(query)
    lib = _load()
    if lib is None:
        raise _Fallback("native lib unavailable")
    stats["native"] += 1
    rw.commit()

    # fused one-pass program (parse + predicate + fold per line); the
    # array path below remains for programs past the kernel bounds
    fused = None
    f_aggs = None
    if getattr(lib, "has_fused", False) and len(all_keys) <= 16:
        fused = plan.pack_fused([keymap[k] for k in plan.cols])
        if fused is not None:
            f_aggs = {
                "what": np.array([w for w, _, _ in aggs],
                                 dtype=np.int32),
                "slot": np.array([-1 if k is None else keymap[k]
                                  for k in agg_keys], dtype=np.int32),
            }

    def _replay_line(json_mod, line: bytes) -> None:
        text = line.decode("utf-8", "replace")
        try:
            doc = json_mod.loads(text)
        except ValueError as e:
            raise SQLError(f"invalid JSON line: {e}")
        rec = doc if isinstance(doc, dict) else {"_1": doc}
        if ev.matches(rec):
            ev.accumulate(rec)

    def replay_rows(pad: bytes, rs: np.ndarray, rl: np.ndarray,
                    rows: np.ndarray) -> None:
        import json as json_mod

        stats["replay_blocks"] += 1
        for r in rows:
            stats["bytes_replayed"] += int(rl[r])
            _replay_line(json_mod, bytes(pad[rs[r]:rs[r] + rl[r]]))

    def replay_span(pad, nbytes: int) -> None:
        """Replay a fused-scan span: same per-line semantics as
        replay_rows, with line splitting done here (the fused kernel
        materializes no row-extent arrays)."""
        import json as json_mod

        stats["replay_blocks"] += 1
        stats["bytes_replayed"] += nbytes
        for raw_line in bytes(pad[:nbytes]).split(b"\n"):
            line = raw_line.strip(b" \t\r")
            if line:
                _replay_line(json_mod, line)

    def gen() -> Iterator[bytes]:
        max_rows = 1 << 18
        nk = len(all_keys)
        kbytes = [k.encode() for k in all_keys]
        keys_arr = (ctypes.c_char_p * nk)(*kbytes)
        key_lens = np.array([len(b) for b in kbytes], dtype=np.int32)
        starts = np.empty((nk, max_rows), dtype=np.int32)
        lens = np.empty((nk, max_rows), dtype=np.int32)
        types = np.empty((nk, max_rows), dtype=np.uint8)
        row_start = np.empty(max_rows + 1, dtype=np.int32)
        row_len = np.empty(max_rows, dtype=np.int32)
        consumed = _i64()
        naggs = len(aggs)
        agg_cnt = np.zeros(naggs, dtype=np.int64)
        agg_s = np.zeros(naggs, dtype=np.float64)
        agg_mn = np.zeros(naggs, dtype=np.float64)
        agg_mx = np.zeros(naggs, dtype=np.float64)
        agg_mnp = np.zeros(naggs, dtype=np.int32)
        agg_mnl = np.zeros(naggs, dtype=np.int32)
        agg_mxp = np.zeros(naggs, dtype=np.int32)
        agg_mxl = np.zeros(naggs, dtype=np.int32)
        rows_o = _i64()
        amb_o = _i64()
        returned = 0
        outbuf = bytearray()
        feeder = _Blocks(raw, rw, b"", compression,
                         direct_ok=fused is not None)
        try:
            while True:
                blk = feeder.next()
                if blk is None:
                    break
                addr, blen, final = blk
                off = 0
                while off < blen:
                    pad = feeder.view(off)
                    cbuf = _vp(addr + off)
                    if fused is not None:
                        lib.sel_json_agg_fused(
                            cbuf, blen - off, 1 if final else 0,
                            keys_arr, _ptr(key_lens), nk,
                            fused["nleaves"], _ptr(fused["kind"]),
                            _ptr(fused["slot"]), _ptr(fused["op"]),
                            _ptr(fused["isnum"]), _ptr(fused["fn"]),
                            _ptr(fused["fa"]), _ptr(fused["fb"]),
                            _ptr(fused["num"]), _ptr(fused["aoff"]),
                            _ptr(fused["alen"]), fused["blob"],
                            fused["mask"], _ptr(fused["prog"]),
                            fused["prog_len"], _ptr(fused["ecodes"]),
                            _ptr(fused["eops"]), naggs,
                            _ptr(f_aggs["what"]), _ptr(f_aggs["slot"]),
                            _ptr(agg_cnt), _ptr(agg_s), _ptr(agg_mn),
                            _ptr(agg_mx), _ptr(agg_mnp), _ptr(agg_mnl),
                            _ptr(agg_mxp), _ptr(agg_mxl),
                            ctypes.byref(rows_o), ctypes.byref(amb_o),
                            ctypes.byref(consumed))
                        cons = int(consumed.value)
                        stats["bytes_scanned"] += cons
                        if amb_o.value > 0:
                            replay_span(pad, cons)
                        else:
                            results = []
                            for ai, (what, colname, fname) in \
                                    enumerate(aggs):
                                if agg_keys[ai] is None:
                                    results.append(
                                        ("count", int(agg_cnt[ai]), 0.0,
                                         None, None))
                                    continue
                                lo = hi = None
                                if what == 2 and int(agg_mnl[ai]) >= 0:
                                    a0 = int(agg_mnp[ai])
                                    l0 = int(agg_mnl[ai])
                                    lo = _num(bytes(pad[a0:a0 + l0])
                                              .decode())
                                    a1 = int(agg_mxp[ai])
                                    l1 = int(agg_mxl[ai])
                                    hi = _num(bytes(pad[a1:a1 + l1])
                                              .decode())
                                results.append((fname, int(agg_cnt[ai]),
                                                float(agg_s[ai]), lo, hi))
                            _commit_agg(ev, results)
                        off += cons
                        if cons == 0:
                            break
                        continue
                    n = lib.sel_json_scan(
                        cbuf, blen - off, 1 if final else 0, keys_arr,
                        _ptr(key_lens), nk, max_rows, _ptr(starts),
                        _ptr(lens), _ptr(types), _ptr(row_start),
                        _ptr(row_len), ctypes.byref(consumed))
                    if n == 0:
                        break
                    n = int(n)
                    ctx = _Ctx()
                    ctx.buf = cbuf
                    ctx.n = n
                    ctx.starts = [starts[keymap[k], :n]
                                  for k in plan.cols]
                    ctx.lens = [lens[keymap[k], :n] for k in plan.cols]
                    ctx.types = [types[keymap[k], :n]
                                 for k in plan.cols]
                    mask = plan.mask(ctx)
                    ambiguous = plan.amb > 0
                    # bad lines mark EVERY key slot 6 (incl. dummy)
                    bad = types[0, :n] == 6
                    if nk > 1:
                        for ki in range(1, nk):
                            bad = bad & (types[ki, :n] == 6)
                    if bad.any() and not plan.cols and agg_keys.count(
                            None) == len(agg_keys):
                        # COUNT(*)-style: kernels never touch types, so
                        # surface bad lines here
                        ambiguous = True
                    if not ambiguous and aggs is not None:
                        results = []
                        kmask = None
                        if mask is not None:
                            kmask = np.ascontiguousarray(
                                mask.astype(np.uint8))
                        for (what, colname, fname), key in zip(
                                aggs, agg_keys):
                            if key is None:
                                if mask is not None:
                                    results.append(
                                        ("count", int(mask.sum()), 0.0,
                                         None, None))
                                else:
                                    results.append(
                                        ("count", n, 0.0, None, None))
                                continue
                            sl = keymap[key]
                            s = _dbl()
                            mn = _dbl()
                            mx = _dbl()
                            am = _i64()
                            ax = _i64()
                            ab = _i64()
                            cnt = lib.sel_json_agg(
                                cbuf, _ptr(starts[sl, :n]),
                                _ptr(lens[sl, :n]),
                                _ptr(types[sl, :n]), n,
                                _ptr(kmask) if kmask is not None
                                else None, what,
                                ctypes.byref(s), ctypes.byref(mn),
                                ctypes.byref(mx), ctypes.byref(am),
                                ctypes.byref(ax), ctypes.byref(ab))
                            if ab.value > 0:
                                ambiguous = True
                                break
                            lo = hi = None
                            if what == 2 and am.value >= 0:
                                a0 = int(starts[sl, am.value])
                                l0 = int(lens[sl, am.value])
                                lo = _num(bytes(pad[a0:a0 + l0])
                                          .decode())
                                a1 = int(starts[sl, ax.value])
                                l1 = int(lens[sl, ax.value])
                                hi = _num(bytes(pad[a1:a1 + l1])
                                          .decode())
                            results.append((fname, int(cnt),
                                            float(s.value), lo, hi))
                        if not ambiguous:
                            _commit_agg(ev, results)
                    if ambiguous:
                        replay_rows(pad, row_start[:n], row_len[:n],
                                    np.arange(n))
                    stats["bytes_scanned"] += int(consumed.value)
                    off += int(consumed.value)
                    if int(consumed.value) == 0:
                        break
                feeder.advance(off)
                if final:
                    break
            outbuf += out.serialize(ev.aggregate_result())
            returned += len(outbuf)
            yield es.records_message(bytes(outbuf))
            if req.request_progress:
                yield es.progress_message(object_size, object_size,
                                          returned)
            yield es.stats_message(object_size, object_size, returned)
            yield es.end_message()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))

    return gen()


# -------------------------------------------------------------- dispatch


def try_native(req, query: Query, rw, object_size: int,
               out) -> Iterator[bytes] | None:
    """Probe + run the native path.  Returns the event-stream iterator,
    or None (with `rw` rewound) when the pyarrow/row paths must take
    over."""
    if not _enabled() or _load() is None:
        rw.rewind()
        return None
    try:
        if "CSV" in req.input_ser:
            return _try_csv(req, query, rw, object_size, out)
        if "JSON" in req.input_ser:
            return _try_json(req, query, rw, object_size, out)
    except _Fallback:
        pass
    stats["fallback"] += 1
    rw.rewind()
    return None
