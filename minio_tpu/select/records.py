"""S3 Select input readers (CSV / JSON, optional gzip) and output writers.

Reference: internal/s3select/csv/reader.go (FileHeaderInfo USE/IGNORE/
NONE, custom delimiters, positional _N columns), internal/s3select/json
(DOCUMENT and LINES types), internal/s3select/select.go (CSV/JSON
output serialization with RecordDelimiter).
"""

from __future__ import annotations

import csv
import gzip
import io
import json
from typing import Iterator

from .sql import SQLError

# Python's csv module caps fields at 128 KiB by default; S3 objects can
# legitimately carry larger cells (the native tier streams them fine),
# so the row engine must not be the tier that chokes first.
csv.field_size_limit(1 << 30)


def _decomp(stream: io.RawIOBase, compression: str) -> io.RawIOBase:
    comp = (compression or "NONE").upper()
    if comp in ("NONE", ""):
        return stream
    if comp == "GZIP":
        return gzip.GzipFile(fileobj=stream)
    if comp == "BZIP2":
        import bz2

        return bz2.BZ2File(stream)
    raise SQLError(f"unsupported CompressionType {compression}")


class CSVInput:
    """Streaming CSV records as dicts.

    header USE  -> keys are the header names (positional _N also works)
    header IGNORE/NONE -> keys are _1.._N only.
    """

    def __init__(self, stream, header_info: str = "USE",
                 delimiter: str = ",", quote: str = '"',
                 record_delimiter: str = "\n", compression: str = "NONE",
                 comment: str = ""):
        self.raw = _decomp(stream, compression)
        text = io.TextIOWrapper(self.raw, encoding="utf-8",
                                errors="replace", newline="")
        self.reader = csv.reader(
            text, delimiter=delimiter or ",", quotechar=quote or '"')
        self.header_info = (header_info or "USE").upper()
        self.comment = comment
        self.header: list[str] | None = None

    def __iter__(self) -> Iterator[dict]:
        first = True
        keys: list[str] = []
        for row in self.reader:
            if not row:
                continue
            if self.comment and row[0].startswith(self.comment):
                continue
            if first:
                first = False
                if self.header_info == "USE":
                    self.header = [h.strip() for h in row]
                    # header-named keys only: SELECT * must not double
                    # the columns; positional _N lookups resolve by
                    # index in the evaluator's fallback
                    keys = [h or f"_{i + 1}"
                            for i, h in enumerate(self.header)]
                    continue
                if self.header_info == "IGNORE":
                    continue
            if len(row) > len(keys):
                keys = keys + [f"_{i + 1}"
                               for i in range(len(keys), len(row))]
            yield dict(zip(keys, row))


class JSONInput:
    """DOCUMENT (one or more whitespace-separated JSON docs) or LINES."""

    def __init__(self, stream, json_type: str = "DOCUMENT",
                 compression: str = "NONE"):
        self.raw = _decomp(stream, compression)
        self.json_type = (json_type or "DOCUMENT").upper()

    def __iter__(self) -> Iterator[dict]:
        if self.json_type == "LINES":
            for line in io.TextIOWrapper(self.raw, encoding="utf-8",
                                         errors="replace"):
                line = line.strip()
                if not line:
                    continue
                yield self._rec(line)
            return
        # DOCUMENT: parse concatenated top-level values
        data = self.raw.read()
        if isinstance(data, bytes):
            data = data.decode("utf-8", "replace")
        dec = json.JSONDecoder()
        idx = 0
        n = len(data)
        while idx < n:
            while idx < n and data[idx] in " \t\r\n":
                idx += 1
            if idx >= n:
                break
            try:
                doc, idx = dec.raw_decode(data, idx)
            except ValueError as e:
                raise SQLError(f"invalid JSON input: {e}")
            if isinstance(doc, list):
                for item in doc:
                    yield self._wrap(item)
            else:
                yield self._wrap(doc)

    def _rec(self, line: str) -> dict:
        try:
            return self._wrap(json.loads(line))
        except ValueError as e:
            raise SQLError(f"invalid JSON line: {e}")

    @staticmethod
    def _wrap(doc) -> dict:
        return doc if isinstance(doc, dict) else {"_1": doc}


class ParquetInput:
    """Parquet records via pyarrow (reference internal/s3select/parquet).

    Parquet needs random access (footer at the tail), so the source
    stream is buffered before parsing; row groups then stream through
    as python dicts."""

    def __init__(self, stream, compression: str = "NONE"):
        if (compression or "NONE").upper() not in ("NONE", ""):
            raise SQLError(
                "CompressionType must be NONE for Parquet input")
        self.raw = stream

    def __iter__(self) -> Iterator[dict]:
        try:
            import pyarrow.parquet as pq
        except ImportError:
            raise SQLError("Parquet input is not supported on this build")
        import tempfile

        # pyarrow needs random access (footer at the tail); spool to a
        # temp file past 64 MiB so multi-GB objects never sit in RAM
        import shutil

        spool = tempfile.SpooledTemporaryFile(max_size=64 << 20)
        shutil.copyfileobj(self.raw, spool, 1 << 20)
        spool.seek(0)
        try:
            pf = pq.ParquetFile(spool)
        except Exception as e:
            spool.close()
            raise SQLError(f"invalid Parquet input: {e}")
        try:
            for batch in pf.iter_batches():
                yield from batch.to_pylist()
        except SQLError:
            raise
        except Exception as e:
            # corrupt data pages surface in-band as InvalidQuery, not
            # as a severed stream / 500
            raise SQLError(f"invalid Parquet input: {e}")
        finally:
            spool.close()


# ------------------------------------------------------------------ output


def _csv_cell(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


class CSVOutput:
    def __init__(self, delimiter: str = ",", record_delimiter: str = "\n",
                 quote: str = '"'):
        self.delim = delimiter or ","
        self.rdelim = record_delimiter or "\n"
        self.quote = quote or '"'

    def serialize(self, rec: dict) -> bytes:
        buf = io.StringIO()
        w = csv.writer(buf, delimiter=self.delim, quotechar=self.quote,
                       lineterminator=self.rdelim)
        w.writerow([_csv_cell(v) for v in rec.values()])
        return buf.getvalue().encode()


class JSONOutput:
    def __init__(self, record_delimiter: str = "\n"):
        self.rdelim = record_delimiter or "\n"

    def serialize(self, rec: dict) -> bytes:
        def default(o):
            return str(o)

        return json.dumps(rec, default=default).encode() + \
            self.rdelim.encode()
