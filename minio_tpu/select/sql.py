"""S3 Select SQL: tokenizer, recursive-descent parser, evaluator.

Reference: internal/s3select/sql (parser.go, evaluate.go, aggregation.go)
— the S3 Select dialect: single-table SELECT over `S3Object` with
projections, WHERE, LIMIT, aggregates, and a small scalar-function
library.  This is an original implementation of the same dialect.

Supported grammar (case-insensitive keywords):

    SELECT <proj> [, <proj>...] FROM <table> [alias] [WHERE <expr>]
                                              [LIMIT <n>]
    proj   := * | expr [AS name]
    expr   := or-chain of AND-chains of comparisons
    cmp    := add (=|!=|<>|<|<=|>|>=) add | add [NOT] LIKE pattern
              | add [NOT] IN (expr,...) | add [NOT] BETWEEN a AND b
              | add IS [NOT] NULL | NOT cmp
    add    := mul ((+|-) mul)* ; mul := unary ((*|/|%) unary)*
    unary  := [-] primary
    primary:= literal | column | function(args) | (expr)
    column := name | alias.name | "quoted name" | s.[_1] style positions
    funcs  := COUNT SUM MIN MAX AVG (aggregate)
              LOWER UPPER LENGTH CHAR_LENGTH TRIM LTRIM RTRIM SUBSTRING
              CAST(x AS INT|INTEGER|FLOAT|DECIMAL|STRING|BOOL|TIMESTAMP)
              COALESCE NULLIF ABS
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class SQLError(Exception):
    """Maps to S3 error InvalidQuery / ParseSelectFailure."""


# ----------------------------------------------------------------- lexer

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>\d+\.\d*|\.\d+|\d+)
    | (?P<dqstring>"(?:[^"]|"")*")
    | (?P<string>'(?:[^']|'')*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<bracket>\[[^\]]*\])
    | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|/|%|\+|-|\.|;)
    )""", re.VERBOSE)

KEYWORDS = {
    "select", "from", "where", "limit", "as", "and", "or", "not", "like",
    "escape", "in", "between", "is", "null", "true", "false", "cast",
}


@dataclass
class Tok:
    kind: str  # number|string|ident|op|kw|bracket
    val: str


def tokenize(s: str) -> list[Tok]:
    out: list[Tok] = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            rest = s[pos:].strip()
            if not rest:
                break
            raise SQLError(f"unexpected character at: {rest[:20]!r}")
        pos = m.end()
        if m.lastgroup == "number":
            out.append(Tok("number", m.group("number")))
        elif m.lastgroup == "string":
            out.append(Tok("string",
                           m.group("string")[1:-1].replace("''", "'")))
        elif m.lastgroup == "dqstring":
            out.append(Tok("qident",
                           m.group("dqstring")[1:-1].replace('""', '"')))
        elif m.lastgroup == "ident":
            v = m.group("ident")
            out.append(Tok("kw" if v.lower() in KEYWORDS else "ident", v))
        elif m.lastgroup == "bracket":
            out.append(Tok("bracket", m.group("bracket")[1:-1]))
        else:
            out.append(Tok("op", m.group("op")))
    return out


# ------------------------------------------------------------------- AST


@dataclass
class Lit:
    v: object


@dataclass
class Col:
    name: str           # column name, or _N positional
    def __post_init__(self):
        self.lower = self.name.lower()


@dataclass
class Star:
    pass


@dataclass
class Un:
    op: str
    e: object


@dataclass
class Bin:
    op: str
    l: object
    r: object


@dataclass
class Like:
    e: object
    pat: object
    negate: bool
    esc: object = None


@dataclass
class InList:
    e: object
    items: list
    negate: bool


@dataclass
class Between:
    e: object
    lo: object
    hi: object
    negate: bool


@dataclass
class IsNull:
    e: object
    negate: bool


@dataclass
class Func:
    name: str
    args: list
    star: bool = False  # COUNT(*)


@dataclass
class Cast:
    e: object
    typ: str


@dataclass
class Projection:
    expr: object
    alias: str = ""


@dataclass
class Query:
    projections: list[Projection] = field(default_factory=list)
    star: bool = False
    where: object = None
    limit: int | None = None
    table_alias: str = ""


AGGREGATES = {"count", "sum", "min", "max", "avg"}
SCALARS = {
    "lower", "upper", "length", "char_length", "character_length", "trim",
    "ltrim", "rtrim", "substring", "coalesce", "nullif", "abs", "utcnow",
}


class Parser:
    def __init__(self, toks: list[Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Tok | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tok:
        t = self.peek()
        if t is None:
            raise SQLError("unexpected end of query")
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> str | None:
        t = self.peek()
        if t and t.kind == "kw" and t.val.lower() in kws:
            self.i += 1
            return t.val.lower()
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SQLError(f"expected {kw.upper()}")

    def accept_op(self, *ops: str) -> str | None:
        t = self.peek()
        if t and t.kind == "op" and t.val in ops:
            self.i += 1
            return t.val
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            got = self.peek()
            raise SQLError(f"expected {op!r}, got {got.val if got else 'EOF'}")

    # -- grammar ------------------------------------------------------------
    def parse(self) -> Query:
        q = Query()
        self.expect_kw("select")
        if self.accept_op("*"):
            q.star = True
        else:
            q.projections.append(self.projection())
            while self.accept_op(","):
                q.projections.append(self.projection())
        self.expect_kw("from")
        self.table(q)
        if self.accept_kw("where"):
            q.where = self.expr()
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "number" or "." in t.val:
                raise SQLError("LIMIT expects an integer")
            q.limit = int(t.val)
        self.accept_op(";")
        if self.peek() is not None:
            raise SQLError(f"trailing tokens near {self.peek().val!r}")
        return q

    def table(self, q: Query) -> None:
        t = self.next()
        name = t.val
        if t.kind not in ("ident", "qident", "bracket"):
            raise SQLError("bad FROM clause")
        if name.lower() not in ("s3object", "s3objects"):
            raise SQLError("FROM must reference S3Object")
        # optional .something path (JSON documents) — consumed, top-level
        while self.accept_op("."):
            self.next()
        t = self.peek()
        if t and t.kind == "ident":
            q.table_alias = self.next().val.lower()

    def projection(self) -> Projection:
        e = self.expr()
        alias = ""
        if self.accept_kw("as"):
            t = self.next()
            if t.kind not in ("ident", "qident"):
                raise SQLError("bad alias")
            alias = t.val
        return Projection(e, alias)

    def expr(self):
        e = self.and_expr()
        while self.accept_kw("or"):
            e = Bin("or", e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.accept_kw("and"):
            e = Bin("and", e, self.not_expr())
        return e

    def not_expr(self):
        if self.accept_kw("not"):
            return Un("not", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self):
        e = self.add_expr()
        t = self.peek()
        negate = False
        if t and t.kind == "kw" and t.val.lower() == "not":
            nxt = self.toks[self.i + 1] if self.i + 1 < len(self.toks) else None
            if nxt and nxt.kind == "kw" and nxt.val.lower() in (
                    "like", "in", "between"):
                self.i += 1
                negate = True
                t = self.peek()
        if t and t.kind == "op" and t.val in ("=", "!=", "<>", "<", "<=",
                                              ">", ">="):
            self.i += 1
            op = "!=" if t.val == "<>" else t.val
            return Bin(op, e, self.add_expr())
        if self.accept_kw("like"):
            pat = self.add_expr()
            esc = None
            if self.accept_kw("escape"):
                esc = self.add_expr()
            return Like(e, pat, negate, esc)
        if self.accept_kw("in"):
            self.expect_op("(")
            items = [self.expr()]
            while self.accept_op(","):
                items.append(self.expr())
            self.expect_op(")")
            return InList(e, items, negate)
        if self.accept_kw("between"):
            lo = self.add_expr()
            self.expect_kw("and")
            hi = self.add_expr()
            return Between(e, lo, hi, negate)
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return IsNull(e, neg)
        return e

    def add_expr(self):
        e = self.mul_expr()
        while True:
            op = self.accept_op("+", "-")
            if not op:
                return e
            e = Bin(op, e, self.mul_expr())

    def mul_expr(self):
        e = self.unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return e
            e = Bin(op, e, self.unary())

    def unary(self):
        if self.accept_op("-"):
            return Un("neg", self.unary())
        if self.accept_op("+"):
            return self.unary()
        return self.primary()

    def primary(self):
        t = self.next()
        if t.kind == "number":
            return Lit(float(t.val) if "." in t.val else int(t.val))
        if t.kind == "string":
            return Lit(t.val)
        if t.kind == "kw":
            kw = t.val.lower()
            if kw == "null":
                return Lit(None)
            if kw == "true":
                return Lit(True)
            if kw == "false":
                return Lit(False)
            if kw == "cast":
                self.expect_op("(")
                e = self.expr()
                self.expect_kw("as")
                ty = self.next()
                if ty.kind not in ("ident", "kw"):
                    raise SQLError("bad CAST type")
                self.expect_op(")")
                return Cast(e, ty.val.lower())
            raise SQLError(f"unexpected keyword {t.val!r}")
        if t.kind == "op" and t.val == "(":
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "bracket":
            return Col(t.val)
        if t.kind in ("ident", "qident"):
            name = t.val
            # function call?
            if t.kind == "ident" and self.accept_op("("):
                fname = name.lower()
                if fname not in AGGREGATES and fname not in SCALARS:
                    raise SQLError(f"unknown function {name!r}")
                if fname == "count" and self.accept_op("*"):
                    self.expect_op(")")
                    return Func("count", [], star=True)
                args = []
                if not self.accept_op(")"):
                    args.append(self.expr())
                    while self.accept_op(","):
                        args.append(self.expr())
                    self.expect_op(")")
                return Func(fname, args)
            # dotted path: alias.col or record.path
            parts = [name]
            while self.accept_op("."):
                nt = self.next()
                if nt.kind == "bracket":
                    parts.append(nt.val)
                elif nt.kind in ("ident", "qident"):
                    parts.append(nt.val)
                else:
                    raise SQLError("bad column path")
            return Col(".".join(parts))
        raise SQLError(f"unexpected token {t.val!r}")


def parse(query: str) -> Query:
    return Parser(tokenize(query)).parse()


# -------------------------------------------------------------- evaluate


def _like_to_re(pat: str, esc: str | None) -> re.Pattern:
    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if esc and c == esc and i + 1 < len(pat):
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def _num(v):
    """Coerce a CSV string (everything is text) to a number if possible."""
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            try:
                return float(v)
            except ValueError:
                return v
    return v


def _cmp_pair(a, b):
    a2, b2 = _num(a), _num(b)
    if isinstance(a2, (int, float)) and not isinstance(a2, bool) \
            and isinstance(b2, (int, float)) and not isinstance(b2, bool):
        return a2, b2
    return str(a), str(b)


class Evaluator:
    """Evaluates a parsed query against record dicts."""

    def __init__(self, q: Query):
        self.q = q
        self._agg = any(
            isinstance(p.expr, Func) and p.expr.name in AGGREGATES
            for p in q.projections)
        if self._agg and any(
                not (isinstance(p.expr, Func)
                     and p.expr.name in AGGREGATES)
                for p in q.projections):
            raise SQLError(
                "cannot mix aggregate and non-aggregate projections")
        self._agg_state = [dict(count=0, sum=0.0, min=None, max=None)
                           for _ in q.projections]

    @property
    def is_aggregate(self) -> bool:
        return self._agg

    # -- scalar evaluation ---------------------------------------------------
    def value(self, e, rec: dict):
        if isinstance(e, Lit):
            return e.v
        if isinstance(e, Col):
            return self._col(e, rec)
        if isinstance(e, Un):
            v = self.value(e.e, rec)
            if e.op == "neg":
                v = _num(v)
                if not isinstance(v, (int, float)):
                    raise SQLError("cannot negate non-number")
                return -v
            return not self._truth(v)
        if isinstance(e, Bin):
            return self._bin(e, rec)
        if isinstance(e, Like):
            v = self.value(e.e, rec)
            if v is None:
                return None
            pat = self.value(e.pat, rec)
            escv = self.value(e.esc, rec) if e.esc is not None else None
            ok = bool(_like_to_re(str(pat), escv).match(str(v)))
            return not ok if e.negate else ok
        if isinstance(e, InList):
            v = self.value(e.e, rec)
            if v is None:
                return None  # SQL 3VL: NULL [NOT] IN (...) is NULL
            vals = [self.value(x, rec) for x in e.items]
            hit = any(self._eq(v, x) for x in vals)
            return not hit if e.negate else hit
        if isinstance(e, Between):
            v = self.value(e.e, rec)
            if v is None:
                return None  # SQL 3VL: NULL [NOT] BETWEEN is NULL
            lo = self.value(e.lo, rec)
            hi = self.value(e.hi, rec)
            a, l2 = _cmp_pair(v, lo)
            b, h2 = _cmp_pair(v, hi)
            ok = l2 <= a and b <= h2
            return not ok if e.negate else ok
        if isinstance(e, IsNull):
            v = self.value(e.e, rec)
            isnull = v is None or v == ""
            return not isnull if e.negate else isnull
        if isinstance(e, Cast):
            return self._cast(self.value(e.e, rec), e.typ)
        if isinstance(e, Func):
            return self._scalar_fn(e, rec)
        if isinstance(e, Star):
            return rec
        raise SQLError(f"cannot evaluate {type(e).__name__}")

    def _col(self, c: Col, rec: dict):
        name = c.name
        alias = self.q.table_alias
        parts = name.split(".")
        if alias and parts and parts[0].lower() == alias:
            parts = parts[1:]
        if not parts:
            return rec
        cur = rec
        for p in parts:
            if isinstance(cur, dict):
                if p in cur:
                    cur = cur[p]
                    continue
                # case-insensitive fallback
                lowered = {k.lower(): v for k, v in cur.items()}
                if p.lower() in lowered:
                    cur = lowered[p.lower()]
                    continue
                # positional _N over a named-column record (CSV with
                # FileHeaderInfo=USE keeps only header keys)
                if re.fullmatch(r"_\d+", p):
                    vals = list(cur.values())
                    i = int(p[1:]) - 1
                    if 0 <= i < len(vals):
                        cur = vals[i]
                        continue
                return None
            elif isinstance(cur, list):
                try:
                    cur = cur[int(p.lstrip("_")) - 1]
                except (ValueError, IndexError):
                    return None
            else:
                return None
        return cur

    @staticmethod
    def _truth(v) -> bool:
        if v is None:
            return False
        if isinstance(v, bool):
            return v
        if isinstance(v, str):
            return v.lower() == "true"
        return bool(v)

    def _eq(self, a, b) -> bool:
        if a is None or b is None:
            return False
        x, y = _cmp_pair(a, b)
        return x == y

    def _bin(self, e: Bin, rec: dict):
        if e.op == "and":
            return self._truth(self.value(e.l, rec)) and \
                self._truth(self.value(e.r, rec))
        if e.op == "or":
            return self._truth(self.value(e.l, rec)) or \
                self._truth(self.value(e.r, rec))
        lv = self.value(e.l, rec)
        rv = self.value(e.r, rec)
        if e.op in ("=", "!="):
            eq = self._eq(lv, rv)
            return eq if e.op == "=" else (
                False if lv is None or rv is None else not eq)
        if e.op in ("<", "<=", ">", ">="):
            if lv is None or rv is None:
                return False
            a, b = _cmp_pair(lv, rv)
            try:
                return {"<": a < b, "<=": a <= b,
                        ">": a > b, ">=": a >= b}[e.op]
            except TypeError:
                raise SQLError("incomparable operands")
        # arithmetic
        a, b = _num(lv), _num(rv)
        if not isinstance(a, (int, float)) or isinstance(a, bool) \
                or not isinstance(b, (int, float)) or isinstance(b, bool):
            raise SQLError(f"arithmetic on non-numbers: {lv!r} {e.op} {rv!r}")
        if e.op == "+":
            return a + b
        if e.op == "-":
            return a - b
        if e.op == "*":
            return a * b
        if e.op == "/":
            if b == 0:
                raise SQLError("division by zero")
            return a / b
        if e.op == "%":
            if b == 0:
                raise SQLError("division by zero")
            return a % b
        raise SQLError(f"bad operator {e.op}")

    def _cast(self, v, typ: str):
        if v is None:
            return None
        try:
            if typ in ("int", "integer"):
                # int(float('inf')) raises OverflowError, not
                # ValueError — it must surface as a SQL error event,
                # never sever the stream (round-5 fuzz finding)
                try:
                    return int(float(v))
                except OverflowError:
                    raise ValueError(f"non-finite value {v!r}")
            if typ in ("float", "decimal", "numeric", "double"):
                return float(v)
            if typ in ("string", "varchar", "char"):
                return str(v)
            if typ in ("bool", "boolean"):
                return self._truth(v)
            if typ == "timestamp":
                return str(v)
        except (ValueError, TypeError):
            raise SQLError(f"cannot CAST {v!r} to {typ}")
        raise SQLError(f"unsupported CAST type {typ}")

    def _scalar_fn(self, f: Func, rec: dict):
        args = [self.value(a, rec) for a in f.args]
        n = f.name
        if n in AGGREGATES:
            raise SQLError("aggregate in scalar context")
        if n == "lower":
            return None if args[0] is None else str(args[0]).lower()
        if n == "upper":
            return None if args[0] is None else str(args[0]).upper()
        if n in ("length", "char_length", "character_length"):
            return None if args[0] is None else len(str(args[0]))
        if n == "trim":
            return None if args[0] is None else str(args[0]).strip()
        if n == "ltrim":
            return None if args[0] is None else str(args[0]).lstrip()
        if n == "rtrim":
            return None if args[0] is None else str(args[0]).rstrip()
        if n == "substring":
            if args[0] is None:
                return None
            s = str(args[0])
            start = int(_num(args[1])) if len(args) > 1 else 1
            ln = int(_num(args[2])) if len(args) > 2 else None
            start0 = max(start - 1, 0)
            return s[start0:start0 + ln] if ln is not None else s[start0:]
        if n == "coalesce":
            for a in args:
                if a is not None and a != "":
                    return a
            return None
        if n == "nullif":
            return None if self._eq(args[0], args[1]) else args[0]
        if n == "abs":
            v = _num(args[0])
            if not isinstance(v, (int, float)):
                raise SQLError("ABS expects a number")
            return abs(v)
        if n == "utcnow":
            import time

            return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        raise SQLError(f"unknown function {n}")

    # -- per-record driving --------------------------------------------------
    def matches(self, rec: dict) -> bool:
        if self.q.where is None:
            return True
        return self._truth(self.value(self.q.where, rec))

    def project(self, rec: dict) -> dict:
        """Non-aggregate projection of one record."""
        if self.q.star:
            return rec
        out = {}
        for i, p in enumerate(self.q.projections):
            name = p.alias or self._auto_name(p.expr, i)
            out[name] = self.value(p.expr, rec)
        return out

    def accumulate(self, rec: dict) -> None:
        for i, p in enumerate(self.q.projections):
            f = p.expr
            st = self._agg_state[i]
            if f.star:
                st["count"] += 1
                continue
            v = self.value(f.args[0], rec) if f.args else None
            if v is None or v == "":
                continue
            st["count"] += 1
            if f.name in ("sum", "avg"):
                nv = _num(v)
                if not isinstance(nv, (int, float)) or isinstance(nv, bool):
                    raise SQLError(f"{f.name.upper()} over non-number")
                st["sum"] += nv
            if f.name in ("min", "max"):
                nv = _num(v)
                if st["min"] is None:
                    st["min"] = st["max"] = nv
                else:
                    a, b = _cmp_pair(nv, st["min"])
                    if a < b:
                        st["min"] = nv
                    a, b = _cmp_pair(nv, st["max"])
                    if a > b:
                        st["max"] = nv

    def aggregate_result(self) -> dict:
        out = {}
        for i, p in enumerate(self.q.projections):
            f = p.expr
            name = p.alias or self._auto_name(f, i)
            st = self._agg_state[i]
            if f.name == "count":
                out[name] = st["count"]
            elif f.name == "sum":
                out[name] = st["sum"] if st["count"] else None
            elif f.name == "avg":
                out[name] = (st["sum"] / st["count"]) if st["count"] else None
            elif f.name == "min":
                out[name] = st["min"]
            elif f.name == "max":
                out[name] = st["max"]
        return out

    @staticmethod
    def _auto_name(e, i: int) -> str:
        if isinstance(e, Col):
            return e.name.split(".")[-1]
        return f"_{i + 1}"


# ------------------------------------------------- compiled evaluation
#
# The per-record tree walk above (Evaluator.value) pays isinstance
# dispatch + attribute loads for every AST node on every record; for
# queries the vectorized tiers cannot take (functions, CAST,
# arithmetic), that walk IS the scan cost.  compile_predicate/
# compile_projection translate the AST ONCE into nested closures with
# all constants (literals, coerced numbers, LIKE regexes, operator
# functions) bound at compile time — semantics identical to value().
# (Reference analogue: the evaluator pre-binds per-query state in
# internal/s3select/sql/statement.go.)

import operator as _op

_ORD_OPS = {"<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge}


def _compile_expr(e, ev: "Evaluator"):
    """AST node -> fn(rec) -> value, replicating Evaluator.value."""
    if isinstance(e, Lit):
        v = e.v
        return lambda rec: v
    if isinstance(e, Col):
        alias = ev.q.table_alias
        parts = e.name.split(".")
        if alias and parts and parts[0].lower() == alias:
            parts = parts[1:]
        if len(parts) == 1:
            k = parts[0]

            def col(rec, k=k, e=e, ev=ev):
                try:
                    return rec[k]
                except (KeyError, TypeError):
                    return ev._col(e, rec)  # ci/_N/nested fallback
            return col
        return lambda rec, e=e, ev=ev: ev._col(e, rec)
    if isinstance(e, Un):
        inner = _compile_expr(e.e, ev)
        if e.op == "neg":
            def neg(rec, inner=inner):
                v = _num(inner(rec))
                if not isinstance(v, (int, float)):
                    raise SQLError("cannot negate non-number")
                return -v
            return neg
        tr = ev._truth
        return lambda rec, inner=inner, tr=tr: not tr(inner(rec))
    if isinstance(e, Bin):
        lf = _compile_expr(e.l, ev)
        rf = _compile_expr(e.r, ev)
        tr = ev._truth
        if e.op == "and":
            return lambda rec: tr(lf(rec)) and tr(rf(rec))
        if e.op == "or":
            return lambda rec: tr(lf(rec)) or tr(rf(rec))
        if e.op in ("=", "!="):
            eq = ev._eq
            if e.op == "=":
                return lambda rec: eq(lf(rec), rf(rec))

            def ne(rec):
                lv, rv = lf(rec), rf(rec)
                if lv is None or rv is None:
                    return False
                return not eq(lv, rv)
            return ne
        if e.op in _ORD_OPS:
            cmpf = _ORD_OPS[e.op]

            def ordcmp(rec, cmpf=cmpf):
                lv, rv = lf(rec), rf(rec)
                if lv is None or rv is None:
                    return False
                a, b = _cmp_pair(lv, rv)
                try:
                    return cmpf(a, b)
                except TypeError:
                    raise SQLError("incomparable operands")
            return ordcmp
        opc = e.op

        def arith(rec, opc=opc):
            a, b = _num(lf(rec)), _num(rf(rec))
            if not isinstance(a, (int, float)) or isinstance(a, bool) \
                    or not isinstance(b, (int, float)) \
                    or isinstance(b, bool):
                raise SQLError(
                    f"arithmetic on non-numbers: {a!r} {opc} {b!r}")
            if opc == "+":
                return a + b
            if opc == "-":
                return a - b
            if opc == "*":
                return a * b
            if b == 0:
                raise SQLError("division by zero")
            return a / b if opc == "/" else a % b
        return arith
    if isinstance(e, Like):
        vf = _compile_expr(e.e, ev)
        negate = e.negate
        if isinstance(e.pat, Lit) and (
                e.esc is None or isinstance(e.esc, Lit)):
            # constant pattern: regex compiled ONCE (value() recompiles
            # per record)
            rx = _like_to_re(str(e.pat.v),
                             str(e.esc.v) if e.esc is not None else None)

            def like(rec, rx=rx, negate=negate):
                v = vf(rec)
                if v is None:
                    return None
                ok = bool(rx.match(str(v)))
                return not ok if negate else ok
            return like
        pf = _compile_expr(e.pat, ev)
        ef = _compile_expr(e.esc, ev) if e.esc is not None else None

        def like_dyn(rec):
            v = vf(rec)
            if v is None:
                return None
            ok = bool(_like_to_re(
                str(pf(rec)), ef(rec) if ef else None).match(str(v)))
            return not ok if negate else ok
        return like_dyn
    if isinstance(e, InList):
        vf = _compile_expr(e.e, ev)
        fns = [_compile_expr(x, ev) for x in e.items]
        negate = e.negate
        eq = ev._eq

        def inlist(rec):
            v = vf(rec)
            if v is None:
                return None  # SQL 3VL, as in Evaluator.value
            hit = any(eq(v, f(rec)) for f in fns)
            return not hit if negate else hit
        return inlist
    if isinstance(e, Between):
        vf = _compile_expr(e.e, ev)
        lof = _compile_expr(e.lo, ev)
        hif = _compile_expr(e.hi, ev)
        negate = e.negate

        def between(rec):
            v = vf(rec)
            if v is None:
                return None  # SQL 3VL, as in Evaluator.value
            a, l2 = _cmp_pair(v, lof(rec))
            b, h2 = _cmp_pair(v, hif(rec))
            ok = l2 <= a and b <= h2
            return not ok if negate else ok
        return between
    if isinstance(e, IsNull):
        vf = _compile_expr(e.e, ev)
        negate = e.negate

        def isnull(rec):
            v = vf(rec)
            r = v is None or v == ""
            return not r if negate else r
        return isnull
    if isinstance(e, Cast):
        vf = _compile_expr(e.e, ev)
        typ = e.typ
        return lambda rec: ev._cast(vf(rec), typ)
    if isinstance(e, Func):
        # bind arg closures; dispatch resolved once via a Func shim
        # that reuses _scalar_fn's semantics on prepared values
        shim = Func(e.name, [Lit(None) for _ in e.args], star=e.star)
        argfs = [_compile_expr(a, ev) for a in e.args]

        def func(rec, shim=shim, argfs=argfs):
            for lit, f in zip(shim.args, argfs):
                lit.v = f(rec)
            return ev._scalar_fn(shim, rec)
        return func
    # Star or anything exotic: fall back to the interpreter
    return lambda rec: ev.value(e, rec)


def compile_predicate(ev: "Evaluator"):
    """-> fn(rec) -> bool equivalent to ev.matches."""
    if ev.q.where is None:
        return lambda rec: True
    f = _compile_expr(ev.q.where, ev)
    tr = ev._truth
    return lambda rec: tr(f(rec))


def compile_projection(ev: "Evaluator"):
    """-> fn(rec) -> dict equivalent to ev.project."""
    if ev.q.star:
        return lambda rec: rec
    items = [
        (p.alias or Evaluator._auto_name(p.expr, i),
         _compile_expr(p.expr, ev))
        for i, p in enumerate(ev.q.projections)
    ]
    return lambda rec: {k: f(rec) for k, f in items}
