"""AWS event-stream binary framing for SelectObjectContent responses.

Reference: internal/s3select/message.go — the response body is a
sequence of messages, each:

    [4B total-length][4B headers-length][4B prelude CRC32]
    [headers][payload][4B message CRC32]

Headers are (1B name-len)(name)(1B type=7 string)(2B value-len)(value).
Events: Records (payload = serialized rows), Progress/Stats (XML
payload), Cont (keepalive), End.  The S3 SDKs parse exactly this.
"""

from __future__ import annotations

import struct
import zlib


def _header(name: str, value: str) -> bytes:
    nb = name.encode()
    vb = value.encode()
    return bytes([len(nb)]) + nb + b"\x07" + struct.pack(">H", len(vb)) + vb


def message(headers: list[tuple[str, str]], payload: bytes) -> bytes:
    hdrs = b"".join(_header(k, v) for k, v in headers)
    total = 16 + len(hdrs) + len(payload)
    prelude = struct.pack(">II", total, len(hdrs))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude) & 0xFFFFFFFF)
    body = prelude + prelude_crc + hdrs + payload
    return body + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF)


def records_message(payload: bytes) -> bytes:
    return message([
        (":message-type", "event"),
        (":event-type", "Records"),
        (":content-type", "application/octet-stream"),
    ], payload)


def _stats_xml(scanned: int, processed: int, returned: int) -> bytes:
    return (
        f"<Stats><BytesScanned>{scanned}</BytesScanned>"
        f"<BytesProcessed>{processed}</BytesProcessed>"
        f"<BytesReturned>{returned}</BytesReturned></Stats>"
    ).encode()


def progress_message(scanned: int, processed: int, returned: int) -> bytes:
    return message([
        (":message-type", "event"),
        (":event-type", "Progress"),
        (":content-type", "text/xml"),
    ], _stats_xml(scanned, processed, returned).replace(
        b"Stats>", b"Progress>"))


def stats_message(scanned: int, processed: int, returned: int) -> bytes:
    return message([
        (":message-type", "event"),
        (":event-type", "Stats"),
        (":content-type", "text/xml"),
    ], _stats_xml(scanned, processed, returned))


def cont_message() -> bytes:
    return message([
        (":message-type", "event"),
        (":event-type", "Cont"),
    ], b"")


def end_message() -> bytes:
    return message([
        (":message-type", "event"),
        (":event-type", "End"),
    ], b"")


def error_message(code: str, desc: str) -> bytes:
    return message([
        (":message-type", "error"),
        (":error-code", code),
        (":error-message", desc),
    ], b"")


# ------------------------------------------------------------- decoding
# (test-side helper; also useful for a future client)


def decode_all(data: bytes) -> list[dict]:
    """Parse a concatenated event-stream buffer into
    [{headers: {...}, payload: bytes}, ...] with CRC verification."""
    out = []
    off = 0
    while off < len(data):
        if len(data) - off < 16:
            raise ValueError("truncated prelude")
        total, hlen = struct.unpack_from(">II", data, off)
        (pcrc,) = struct.unpack_from(">I", data, off + 8)
        if zlib.crc32(data[off:off + 8]) & 0xFFFFFFFF != pcrc:
            raise ValueError("prelude CRC mismatch")
        msg = data[off:off + total]
        (mcrc,) = struct.unpack_from(">I", msg, total - 4)
        if zlib.crc32(msg[:-4]) & 0xFFFFFFFF != mcrc:
            raise ValueError("message CRC mismatch")
        hdrs = {}
        p = 12
        end = 12 + hlen
        while p < end:
            nlen = msg[p]
            p += 1
            name = msg[p:p + nlen].decode()
            p += nlen
            typ = msg[p]
            p += 1
            if typ != 7:
                raise ValueError(f"unsupported header type {typ}")
            (vlen,) = struct.unpack_from(">H", msg, p)
            p += 2
            hdrs[name] = msg[p:p + vlen].decode()
            p += vlen
        out.append({"headers": hdrs, "payload": msg[end:total - 4]})
        off += total
    return out
