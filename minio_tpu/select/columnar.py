"""Columnar CSV + JSON LINES fast paths for S3 Select.

The reference accelerates Select with simdjson and a generated-assembly
CSV scanner (internal/s3select/simdj, select_benchmark_test.go); the
equivalent here is pyarrow's C++ CSV/NDJSON parsers plus vectorized
predicate masks and aggregate kernels, so a 1 GiB `SELECT COUNT(*) ...
WHERE` scans at parser speed instead of the per-row Python loop in
sql.Evaluator.

CSV: every column is parsed as a STRING (a two-pass open sniffs the
column names, then reopens with explicit string types), so pyarrow type
inference can never fail on a later batch, projected values reproduce the
raw CSV text byte-for-byte, and predicates replicate the row engine's
exact semantics: a cell that parses as a number compares numerically
against numeric(-looking) literals, anything else compares as text —
including empty cells, matching sql._num/_cmp_pair per element.

JSON LINES: native types ride arrow directly; only int/float/string
columns vectorize (bool and nested columns drop to the row engine, whose
coercions have no byte-exact arrow equivalent).

Eligibility (everything else transparently falls back to the row engine):
- CSV input (single-char delimiter/quote, "\n" records, no comment
  char) or JSON input with Type=LINES
- projections: all plain columns / `*` / all aggregates
  (COUNT/SUM/MIN/MAX/AVG over a column or COUNT(*))
- WHERE: AND/OR/NOT tree over comparisons `col <op> literal` (op in
  =, !=, <, <=, >, >=), `col [NOT] LIKE 'pat' [ESCAPE e]`,
  `col [NOT] IN (literals)`, `col [NOT] BETWEEN lit AND lit`,
  `col IS [NOT] NULL`, or absent

Known divergences from the row engine (documented, all garbage-data
corner cases): structurally ragged CSV rows (wrong column count) error
in-band instead of being padded; SUM/AVG over *fractional* values may
differ in the final ulp (vectorized vs sequential float accumulation);
JSON `SELECT *` omits keys that are null/missing (the row engine omits
missing keys but keeps explicit nulls); a JSON type conflict in a later
block errors in-band.

Disable with MINIO_TPU_SELECT_COLUMNAR=0.
"""

from __future__ import annotations

import io
import operator
import os
import re
from itertools import chain
from typing import Iterator

from . import eventstream as es
from .records import _decomp
from .sql import (AGGREGATES, Between, Bin, Col, Evaluator, Func, InList,
                  IsNull, Like, Lit, Query, SQLError, Un, _cmp_pair,
                  _like_to_re, _num)

# flush size mirrors run_select
FLUSH = 256 << 10

# observability: how often the fast path engaged vs fell back
stats = {"fast": 0, "fallback": 0}


class _Fallback(Exception):
    """Raised when the probe shows the fast path cannot honor row-engine
    semantics for this query (unknown column, unsupported shape)."""


class Rewindable:
    """Byte-stream wrapper recording reads so probes can rewind() any
    number of times; commit() stops recording and drops history."""

    def __init__(self, raw):
        self.raw = raw
        self._buf = bytearray()
        self._pos = 0  # logical offset into recorded history
        self._recording = True

    def read(self, n: int = -1):
        out = b""
        if self._pos < len(self._buf):
            if n is None or n < 0:
                out = bytes(self._buf[self._pos:])
            else:
                out = bytes(self._buf[self._pos:self._pos + n])
            self._pos += len(out)
            if n is not None and 0 <= n == len(out):
                return out
            n = -1 if n is None or n < 0 else n - len(out)
        data = self.raw.read(n) or b""
        if self._recording and data:
            self._buf += data
        elif not self._recording and self._buf and self._pos >= len(self._buf):
            self._buf = bytearray()  # replay done: free the prefix
            self._pos = 0
        self._pos += len(data)
        return out + data

    def rewind(self) -> None:
        self._pos = 0

    def readinto(self, b) -> int:
        """Read directly into a caller buffer.  Once committed with no
        replay prefix pending this delegates to the source's readinto —
        one copy instead of two, which matters to scan consumers whose
        kernels run at memcpy speed."""
        if self._recording or self._pos < len(self._buf):
            data = self.read(len(b))
            n = len(data)
            b[:n] = data
            return n
        ri = getattr(self.raw, "readinto", None)
        if ri is not None:
            try:
                return ri(b) or 0
            except (NotImplementedError, io.UnsupportedOperation):
                pass  # io.RawIOBase subclasses may leave the default
        data = self.raw.read(len(b)) or b""
        n = len(data)
        b[:n] = data
        return n

    def direct_buffer(self):
        """Zero-copy view of the remaining stream when the committed
        source is fully memory-resident (BytesIO), else None.  The
        source is advanced to EOF — the caller owns the returned view
        (treat as read-only) and every byte in it."""
        if self._recording or self._pos < len(self._buf):
            return None
        raw = self.raw
        if not isinstance(raw, io.BytesIO):
            return None
        pos = raw.tell()
        mv = raw.getbuffer()
        out = mv[pos:]
        raw.seek(0, 2)
        return out

    def stop_recording(self) -> None:
        """Keep the already-buffered prefix for replay but stop growing
        it — the row-engine fallback must not retain the whole object."""
        self._recording = False

    def commit(self) -> None:
        # drop history already consumed; stop recording new reads
        self._buf = self._buf[self._pos:]
        self._pos = 0
        self._recording = False

    # file-like protocol bits pyarrow/gzip/TextIOWrapper probe for
    closed = False

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return False

    def writable(self) -> bool:
        return False

    def flush(self) -> None:
        pass

    def close(self) -> None:
        # pyarrow closes its source on reader teardown; the row engine may
        # still need to replay, so closing is a caller decision, not ours
        pass


def _enabled() -> bool:
    return os.environ.get("MINIO_TPU_SELECT_COLUMNAR", "1") != "0"


def _shape_ok(q: Query) -> bool:
    """Query-shape eligibility shared by the CSV and JSON fast paths."""
    if not _where_ok(q.where):
        return False
    if q.star and not q.projections:
        return True
    aggs = [isinstance(p.expr, Func) and p.expr.name in AGGREGATES
            for p in q.projections]
    if aggs and all(aggs):
        return all(
            p.expr.star or (len(p.expr.args) == 1
                            and isinstance(p.expr.args[0], Col))
            for p in q.projections
        )
    return bool(q.projections) and all(
        isinstance(p.expr, Col) for p in q.projections
    )


def _eligible(req, q: Query) -> bool:
    """Cheap pre-read eligibility: query + serialization shape only."""
    inp = req.input_ser
    if "CSV" not in inp:
        return False
    c = inp["CSV"] if isinstance(inp["CSV"], dict) else {}
    if (c.get("RecordDelimiter", "\n") or "\n") != "\n":
        return False
    if len(c.get("FieldDelimiter", ",") or ",") != 1:
        return False
    if len(c.get("QuoteCharacter", '"') or '"') != 1:
        return False
    if c.get("Comments"):
        return False
    return _shape_ok(q)


def _lit_ok(v) -> bool:
    """Literals the vector compare reproduces exactly.  NULL literals:
    the row engine's comparisons against NULL are always false; stay on
    it rather than comparing "None" text.  Int literals past 2^53 lose
    precision in the float64 arrow compare while the row engine compares
    exact ints."""
    if v is None:
        return False
    if isinstance(v, int) and not isinstance(v, bool) and abs(v) >= 2**53:
        return False
    return True


def _where_ok(e) -> bool:
    if e is None:
        return True
    if isinstance(e, Un):
        return e.op == "not" and _where_ok(e.e)
    if isinstance(e, Like):
        return (isinstance(e.e, Col) and isinstance(e.pat, Lit)
                and isinstance(e.pat.v, str)
                and (e.esc is None or (isinstance(e.esc, Lit)
                                       and isinstance(e.esc.v, str))))
    if isinstance(e, InList):
        return isinstance(e.e, Col) and all(
            isinstance(x, Lit) and _lit_ok(x.v) for x in e.items)
    if isinstance(e, Between):
        return (isinstance(e.e, Col)
                and isinstance(e.lo, Lit) and _lit_ok(e.lo.v)
                and isinstance(e.hi, Lit) and _lit_ok(e.hi.v))
    if isinstance(e, IsNull):
        return isinstance(e.e, Col)
    if isinstance(e, Bin):
        if e.op in ("and", "or"):
            return _where_ok(e.l) and _where_ok(e.r)
        if e.op in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            if isinstance(e.l, Col) and isinstance(e.r, Lit):
                lit = e.r
            elif isinstance(e.l, Lit) and isinstance(e.r, Col):
                lit = e.l
            else:
                return False
            return _lit_ok(lit.v)
    return False


def _resolve(schema_names: list[str], name: str, alias: str,
             header_use: bool) -> int:
    """Column name -> index, mirroring Evaluator._col resolution order:
    alias strip, exact, case-insensitive, positional _N.  Without a
    header row only positional _N names exist (pyarrow's autogenerated
    f0/f1 names must not leak into the query namespace)."""
    parts = name.split(".")
    if alias and parts and parts[0].lower() == alias:
        parts = parts[1:]
    if len(parts) != 1:
        raise _Fallback(f"nested column {name}")
    p = parts[0]
    if header_use:
        if p in schema_names:
            return schema_names.index(p)
        lowered = [s.lower() for s in schema_names]
        if p.lower() in lowered:
            return lowered.index(p.lower())
    if re.fullmatch(r"_\d+", p):
        i = int(p[1:]) - 1
        if 0 <= i < len(schema_names):
            return i
    raise _Fallback(f"unknown column {name}")


_OPS = {
    "=": operator.eq, "==": operator.eq,
    "!=": operator.ne, "<>": operator.ne,
    "<": operator.lt, "<=": operator.le,
    ">": operator.gt, ">=": operator.ge,
}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


def _pc_ops():
    import pyarrow.compute as pc

    return {
        "=": pc.equal, "==": pc.equal,
        "!=": pc.not_equal, "<>": pc.not_equal,
        "<": pc.less, "<=": pc.less_equal,
        ">": pc.greater, ">=": pc.greater_equal,
    }


_PC_OPS: dict = {}


class _Cols:
    """Per-batch column accessor with two tiers: a pure-arrow float64
    cast (C++-speed, succeeds only when EVERY cell parses — the common
    clean-data case) and a pandas coercion (NaN where the text does not
    parse) for batches containing empties or garbage."""

    _MISS = object()

    def __init__(self, tbl):
        self.tbl = tbl
        self._str: dict[int, object] = {}
        self._num: dict[int, object] = {}
        self._arrow_num: dict[int, object] = {}
        self._valid: dict[int, object] = {}

    def valid(self, idx: int):
        """bool ndarray of non-null cells (JSON columns carry nulls for
        missing keys; CSV string columns never do)."""
        v = self._valid.get(idx)
        if v is None:
            col = self.tbl.column(idx)
            if col.null_count == 0:
                import numpy as np

                v = np.ones(len(col), dtype=bool)
            else:
                import pyarrow.compute as pc

                v = pc.is_valid(col).to_numpy(zero_copy_only=False)
            self._valid[idx] = v
        return v

    def arrow_nums(self, idx: int):
        """float64 ChunkedArray, or None when any cell fails to parse."""
        n = self._arrow_num.get(idx, self._MISS)
        if n is self._MISS:
            import pyarrow as pa
            import pyarrow.compute as pc

            try:
                n = pc.cast(self.tbl.column(idx), pa.float64())
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                n = None
            self._arrow_num[idx] = n
        return n

    def text(self, idx: int):
        s = self._str.get(idx)
        if s is None:
            s = self.tbl.column(idx).to_pandas().astype(object)
            self._str[idx] = s
        return s

    def nums(self, idx: int):
        n = self._num.get(idx)
        if n is None:
            import pandas as pd

            n = pd.to_numeric(self.text(idx), errors="coerce")
            self._num[idx] = n
        return n


def _compile_where(e, names: list[str], alias: str, header_use: bool,
                   types=None, resolver=None):
    """Predicate AST -> fn(_Cols) -> bool ndarray replicating the row
    engine's per-element semantics exactly: numeric compare where both
    the cell and the literal parse as numbers, text compare otherwise
    (sql._cmp_pair); LIKE/IN/BETWEEN/IS NULL/NOT vectorize by composing
    the same leaves.  Null cells (JSON missing keys) make every
    comparison false, as in the row engine.

    `types` (JSON mode): arrow DataType per column.  Only int/float and
    string columns vectorize exactly; bool columns and numeric-column vs
    text-literal compares raise _Fallback (their row-engine coercions —
    str(True), str(5.0) — have no byte-exact arrow equivalent)."""
    import numpy as np

    if not _PC_OPS:
        _PC_OPS.update(_pc_ops())
    if resolver is None:
        def resolver(nm):
            return _resolve(names, nm, alias, header_use)

    def _check_col(idx: int, want_text_exact: bool = False) -> None:
        if types is None:
            return
        import pyarrow as pa

        t = types[idx]
        numeric = pa.types.is_integer(t) or pa.types.is_floating(t)
        text = pa.types.is_string(t) or pa.types.is_large_string(t)
        if not (numeric or text):
            raise _Fallback(f"unsupported column type {t}")
        if want_text_exact and not text:
            raise _Fallback(f"text compare on {t} column")

    def _mask_np(arrow_bool):
        import pyarrow.compute as pc

        return pc.fill_null(arrow_bool, False).to_numpy(
            zero_copy_only=False).astype(bool)

    def cmp_leaf(idx: int, op: str, lit_v):
        fn = _OPS[op]
        numlit = _num(lit_v) if not isinstance(lit_v, bool) else lit_v
        strlit = str(lit_v)
        pc_fn = _PC_OPS[op]
        is_numlit = isinstance(numlit, (int, float)) \
            and not isinstance(numlit, bool)
        _check_col(idx, want_text_exact=not is_numlit)
        if is_numlit:
            def leaf(c, idx=idx, fn=fn, pc_fn=pc_fn, numlit=numlit,
                     strlit=strlit):
                arrow = c.arrow_nums(idx)
                if arrow is not None:  # clean batch: stay in C++
                    return _mask_np(pc_fn(arrow, float(numlit)))
                num = c.nums(idx)
                isnum = num.notna().to_numpy()
                res = np.zeros(len(isnum), dtype=bool)
                if isnum.any():
                    res[isnum] = fn(num[isnum], numlit).to_numpy()
                rest = ~isnum & c.valid(idx)
                if rest.any():
                    res[rest] = fn(
                        c.text(idx)[rest].astype(str), strlit).to_numpy()
                return res
            return leaf

        def leaf(c, idx=idx, pc_fn=pc_fn, strlit=strlit):
            # lexicographic string compare entirely in arrow; a numeric
            # JSON column compares as its text rendering (str(v)), same
            # as the row engine's _cmp_pair string branch
            import pyarrow as pa

            col = c.tbl.column(idx)
            if not pa.types.is_string(col.type) \
                    and not pa.types.is_large_string(col.type):
                col = col.cast(pa.string())
            return _mask_np(pc_fn(col, strlit))
        return leaf

    def comp(node):
        if isinstance(node, Un):  # NOT expr: _truth(None) is False, so
            inner = comp(node.e)   # null rows flip to True — plain ~mask
            return lambda c: ~inner(c)
        if isinstance(node, Like):
            base = _like_to_re(
                str(node.pat.v),
                str(node.esc.v) if node.esc is not None else None)
            # inline (?s) instead of flags= — pandas' match() refuses
            # separate flags with some string backends
            regex = re.compile("(?s)" + base.pattern)
            idx = resolver(node.e.name)
            _check_col(idx, want_text_exact=types is not None)
            negate = node.negate

            def leaf(c, idx=idx, regex=regex, negate=negate):
                s = c.text(idx)
                matched = s.astype(str).str.match(
                    regex.pattern).to_numpy(dtype=bool, na_value=False)
                valid = c.valid(idx)
                # a null value makes LIKE and NOT LIKE both false
                # (row engine returns None either way)
                return (valid & ~matched) if negate else (valid & matched)
            return leaf
        if isinstance(node, InList):
            idx = resolver(node.e.name)
            leaves = [cmp_leaf(idx, "=", x.v) for x in node.items]
            negate = node.negate

            def leaf(c, idx=idx, leaves=leaves, negate=negate):
                m = leaves[0](c)
                for lf in leaves[1:]:
                    m = m | lf(c)
                return (c.valid(idx) & ~m) if negate else m
            return leaf
        if isinstance(node, Between):
            idx = resolver(node.e.name)
            lo = cmp_leaf(idx, ">=", node.lo.v)
            hi = cmp_leaf(idx, "<=", node.hi.v)
            negate = node.negate

            def leaf(c, idx=idx, lo=lo, hi=hi, negate=negate):
                m = lo(c) & hi(c)
                return (c.valid(idx) & ~m) if negate else m
            return leaf
        if isinstance(node, IsNull):
            idx = resolver(node.e.name)
            _check_col(idx)
            negate = node.negate

            def leaf(c, idx=idx, negate=negate):
                import pyarrow as pa
                import pyarrow.compute as pc

                col = c.tbl.column(idx)
                isnull = ~c.valid(idx)
                if pa.types.is_string(col.type) \
                        or pa.types.is_large_string(col.type):
                    # row engine: empty text counts as null
                    isnull = isnull | _mask_np(pc.equal(col, ""))
                return ~isnull if negate else isnull
            return leaf
        if isinstance(node, Bin) and node.op in ("and", "or"):
            lf, rf = comp(node.l), comp(node.r)
            if node.op == "and":
                return lambda c: lf(c) & rf(c)
            return lambda c: lf(c) | rf(c)
        col, lit, flip = node.l, node.r, False
        if isinstance(col, Lit):
            col, lit, flip = node.r, node.l, True
        idx = resolver(col.name)
        op = _FLIP.get(node.op, node.op) if flip else node.op
        return cmp_leaf(idx, op, lit.v)

    return comp(e)


def try_columnar(req, query: Query, rw: Rewindable, object_size: int,
                 out) -> Iterator[bytes] | None:
    """Probe + run the columnar path.  Returns the event-stream iterator,
    or None (with `rw` rewound) when the row engine must take over."""
    if not _enabled():
        rw.rewind()
        return None
    if "JSON" in req.input_ser:
        return _try_json(req, query, rw, object_size, out)
    if "Parquet" in req.input_ser:
        return _try_parquet(req, query, rw, object_size, out)
    if not _eligible(req, query):
        stats["fallback"] += 1
        rw.rewind()
        return None
    try:
        import pyarrow as pa
        import pyarrow.csv as pacsv
    except Exception:  # pragma: no cover - pyarrow baked into this env
        rw.rewind()
        return None

    inp = req.input_ser
    c = inp["CSV"] if isinstance(inp["CSV"], dict) else {}
    header = (c.get("FileHeaderInfo", "USE") or "USE").upper()
    compression = inp.get("CompressionType", "NONE") or "NONE"
    parse_opts = pacsv.ParseOptions(
        delimiter=c.get("FieldDelimiter", ",") or ",",
        quote_char=c.get("QuoteCharacter", '"') or '"',
        newlines_in_values=True,
    )

    # pass 1: sniff column names from the first block, then rewind and
    # reopen with every column pinned to string — no inference, so a
    # later batch can never hit a type-conversion error
    try:
        raw = _decomp(rw, compression)
        sniff = pacsv.open_csv(
            raw,
            read_options=pacsv.ReadOptions(
                block_size=1 << 16,
                autogenerate_column_names=header != "USE",
                skip_rows=1 if header == "IGNORE" else 0,
            ),
            parse_options=parse_opts,
        )
        # raw_names key pyarrow options (they must match the file bytes);
        # `names` are the query/output-facing forms — CSVInput strips
        # header whitespace (records.py header row) so output keys and
        # column resolution must use the stripped spelling
        raw_names = [f.name for f in sniff.schema]
        names = [n.strip() if header == "USE" else n for n in raw_names]
        del sniff
    except (pa.ArrowInvalid, pa.ArrowKeyError, StopIteration, OSError):
        stats["fallback"] += 1
        rw.rewind()
        return None

    alias = query.table_alias
    header_use = header == "USE"
    try:
        mask_fn = (_compile_where(query.where, names, alias, header_use)
                   if query.where is not None else None)
        agg_cols: list[int | None] = []
        proj_cols: list[int] = []
        ev = Evaluator(query)
        if ev.is_aggregate:
            for p in query.projections:
                f = p.expr
                agg_cols.append(
                    None if f.star
                    else _resolve(names, f.args[0].name, alias, header_use))
        elif query.star:
            proj_cols = list(range(len(names)))
        else:
            proj_cols = [
                _resolve(names, p.expr.name, alias, header_use)
                for p in query.projections
            ]
    except _Fallback:
        stats["fallback"] += 1
        rw.rewind()
        return None

    rw.rewind()
    try:
        raw = _decomp(rw, compression)
        reader = pacsv.open_csv(
            raw,
            read_options=pacsv.ReadOptions(
                block_size=4 << 20,
                autogenerate_column_names=header != "USE",
                skip_rows=1 if header == "IGNORE" else 0,
            ),
            parse_options=parse_opts,
            convert_options=pacsv.ConvertOptions(
                column_types={n: pa.string() for n in raw_names},
                strings_can_be_null=False,
            ),
        )
        first = reader.read_next_batch()
    except (pa.ArrowInvalid, pa.ArrowKeyError, StopIteration, OSError):
        stats["fallback"] += 1
        rw.rewind()
        return None

    stats["fast"] += 1
    rw.commit()

    def norm_name(i: int) -> str:
        return names[i] if header_use else f"_{i + 1}"

    def gen() -> Iterator[bytes]:
        import numpy as np

        returned = 0
        buf = bytearray()
        limit = query.limit
        n_out = 0
        try:
            for batch in chain([first], reader):
                if (limit is not None and n_out >= limit
                        and not ev.is_aggregate):
                    break
                tbl = pa.Table.from_batches([batch])
                if mask_fn is not None:
                    mask = mask_fn(_Cols(tbl))
                    if not mask.any():
                        continue
                    if not mask.all():
                        tbl = tbl.filter(pa.array(mask))
                if tbl.num_rows == 0:
                    continue
                if ev.is_aggregate:
                    _accumulate(ev, tbl, agg_cols)
                    continue
                take = tbl.num_rows
                if limit is not None:
                    take = min(take, limit - n_out)
                    tbl = tbl.slice(0, take)
                pull = [tbl.column(i).to_pylist() for i in proj_cols]
                if query.star:
                    keys = [norm_name(i) for i in proj_cols]
                else:
                    keys = [
                        p.alias or Evaluator._auto_name(p.expr, i)
                        for i, p in enumerate(query.projections)
                    ]
                for row in zip(*pull):
                    rec = {
                        k: ("" if v is None else v)
                        for k, v in zip(keys, row)
                    }
                    buf += out.serialize(rec)
                    if len(buf) >= FLUSH:
                        returned += len(buf)
                        yield es.records_message(bytes(buf))
                        buf.clear()
                n_out += take
            if ev.is_aggregate:
                buf += out.serialize(ev.aggregate_result())
            if buf:
                returned += len(buf)
                yield es.records_message(bytes(buf))
            if req.request_progress:
                yield es.progress_message(object_size, object_size, returned)
            yield es.stats_message(object_size, object_size, returned)
            yield es.end_message()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))
        except pa.ArrowInvalid as e:
            # structural CSV errors only (ragged rows) — types can no
            # longer fail since every column is read as string
            yield es.error_message("InvalidQuery", f"CSV parse: {e}")

    return gen()


def _typed_resolver(names: list[str], alias: str):
    """Name-only column resolution for typed sources (JSON/Parquet):
    exact then case-insensitive; no positional _N (an absent '_2' key
    is a missing field, not column 2)."""
    lowered = [s.lower() for s in names]

    def resolver(name: str) -> int:
        parts = name.split(".")
        if alias and parts and parts[0].lower() == alias:
            parts = parts[1:]
        if len(parts) != 1:
            raise _Fallback(f"nested column {name}")
        p = parts[0]
        if p in names:
            return names.index(p)
        if p.lower() in lowered:
            return lowered.index(p.lower())
        raise _Fallback(f"unknown column {name}")

    return resolver


def _typed_agg_cols(query: Query, ev: Evaluator, resolver,
                    types) -> list:
    """Aggregate column indices for typed sources; only int/float/
    string columns fold exactly."""
    import pyarrow as pa

    agg_cols: list[int | None] = []
    for p in query.projections:
        f = p.expr
        if f.star:
            agg_cols.append(None)
            continue
        idx = resolver(f.args[0].name)
        t = types[idx]
        if not (pa.types.is_integer(t) or pa.types.is_floating(t)
                or pa.types.is_string(t) or pa.types.is_large_string(t)):
            raise _Fallback(f"aggregate over {t} column")
        agg_cols.append(idx)
    return agg_cols


def _try_parquet(req, query: Query, rw: Rewindable, object_size: int,
                 out) -> Iterator[bytes] | None:
    """Parquet columnar path: row groups stream as arrow batches with
    the same typed masks/aggregates as the JSON tier, instead of
    per-row dicts through the row engine (reference
    internal/s3select/parquet reads row groups natively too).

    Projections/SELECT * materialize only the MASKED rows via
    to_pylist, which the row engine also uses — values (incl. None,
    timestamps, decimals) render identically."""
    if (req.input_ser.get("CompressionType", "NONE") or "NONE") \
            not in ("NONE", ""):
        rw.rewind()
        return None  # the reader will raise the SQLError, not us
    if not _shape_ok(query):
        stats["fallback"] += 1
        rw.rewind()
        return None
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except Exception:  # pragma: no cover - pyarrow baked into this env
        rw.rewind()
        return None

    import shutil
    import tempfile

    # parquet always needs the whole object (footer at the tail), so
    # commit the rewind buffer BEFORE spooling — recording would pin a
    # full in-RAM copy alongside the disk spool.  Post-spool fallbacks
    # run the row engine FROM THE SPOOL (never from rw again).
    rw.commit()
    spool = tempfile.SpooledTemporaryFile(max_size=64 << 20)

    def spool_fallback():
        from . import row_engine_stream
        from .records import ParquetInput

        stats["fallback"] += 1
        spool.seek(0)

        def gen_fb():
            try:
                yield from row_engine_stream(
                    ParquetInput(spool), query, out, object_size,
                    req.request_progress)
            finally:
                spool.close()

        return gen_fb()

    try:
        shutil.copyfileobj(rw, spool, 1 << 20)
        spool.seek(0)
        pf = pq.ParquetFile(spool)
        schema = pf.schema_arrow
    except Exception:
        # bad footer etc: the row engine surfaces its InvalidQuery
        return spool_fallback()

    names = [f.name for f in schema]
    types = [f.type for f in schema]
    alias = query.table_alias
    resolver = _typed_resolver(names, alias)

    ev = Evaluator(query)
    try:
        mask_fn = (_compile_where(query.where, names, alias, True, types,
                                  resolver=resolver)
                   if query.where is not None else None)
        agg_cols: list[int | None] = []
        if ev.is_aggregate:
            agg_cols = _typed_agg_cols(query, ev, resolver, types)
    except _Fallback:
        return spool_fallback()

    stats["fast"] += 1

    from .sql import compile_projection

    project = compile_projection(ev)

    def gen() -> Iterator[bytes]:
        returned = 0
        buf = bytearray()
        limit = query.limit
        n_out = 0
        try:
            try:
                batches = pf.iter_batches()
                for batch in batches:
                    if (limit is not None and n_out >= limit
                            and not ev.is_aggregate):
                        break
                    tbl = pa.Table.from_batches([batch])
                    if mask_fn is not None:
                        mask = mask_fn(_Cols(tbl))
                        if not mask.any():
                            continue
                        if not mask.all():
                            tbl = tbl.filter(pa.array(mask))
                    if tbl.num_rows == 0:
                        continue
                    if ev.is_aggregate:
                        _accumulate(ev, tbl, agg_cols)
                        continue
                    take = tbl.num_rows
                    if limit is not None:
                        take = min(take, limit - n_out)
                        tbl = tbl.slice(0, take)
                    # masked rows only: to_pylist values (None,
                    # datetimes, decimals...) are exactly what the row
                    # engine's reader feeds the compiled projection
                    for rec in tbl.to_pylist():
                        buf += out.serialize(project(rec))
                        if len(buf) >= FLUSH:
                            returned += len(buf)
                            yield es.records_message(bytes(buf))
                            buf.clear()
                    n_out += take
                if ev.is_aggregate:
                    buf += out.serialize(ev.aggregate_result())
                if buf:
                    returned += len(buf)
                    yield es.records_message(bytes(buf))
                if req.request_progress:
                    yield es.progress_message(object_size, object_size,
                                              returned)
                yield es.stats_message(object_size, object_size,
                                       returned)
                yield es.end_message()
            finally:
                spool.close()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))
        except Exception as e:
            # corrupt data pages raise OSError (verified: snappy
            # corruption), not ArrowInvalid — anything mid-stream must
            # become an in-band error, matching records.ParquetInput's
            # broad catch, never a severed connection
            yield es.error_message("InvalidQuery", f"Parquet: {e}")

    return gen()


def _try_json(req, query: Query, rw: Rewindable, object_size: int,
              out) -> Iterator[bytes] | None:
    """JSON LINES fast path: pyarrow's C++ NDJSON parser + the same
    vectorized masks/aggregates as CSV (the simdjson analogue,
    internal/s3select/simdj/reader.go:27).

    Eligibility beyond _shape_ok: Type=LINES; queried columns must be
    int/float/string (native JSON types compare exactly through arrow;
    bool and nested columns drop to the row engine).  Documented
    divergences: SELECT * omits keys that are null/missing (the row
    engine omits missing keys but keeps explicit nulls); a type conflict
    in a later block errors in-band instead of switching semantics
    per-record."""
    j = req.input_ser["JSON"] if isinstance(req.input_ser["JSON"], dict) \
        else {}
    jtype = (j.get("Type", "DOCUMENT") or "DOCUMENT").upper()
    if jtype != "LINES" or not _shape_ok(query):
        stats["fallback"] += 1
        rw.rewind()
        return None
    try:
        import pyarrow as pa
        import pyarrow.json as pajson
    except Exception:  # pragma: no cover - pyarrow baked into this env
        rw.rewind()
        return None

    compression = req.input_ser.get("CompressionType", "NONE") or "NONE"
    try:
        raw = _decomp(rw, compression)
        reader = pajson.open_json(
            raw,
            read_options=pajson.ReadOptions(block_size=4 << 20),
        )
        first = reader.read_next_batch()
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, StopIteration,
            OSError, ValueError):
        stats["fallback"] += 1
        rw.rewind()
        return None

    names = [f.name for f in first.schema]
    types = [f.type for f in first.schema]
    alias = query.table_alias
    ev = Evaluator(query)
    resolver = _typed_resolver(names, alias)

    try:
        mask_fn = (_compile_where(query.where, names, alias, True, types,
                                  resolver=resolver)
                   if query.where is not None else None)
        agg_cols: list[int | None] = []
        proj_cols: list[int] = []
        if ev.is_aggregate:
            agg_cols = _typed_agg_cols(query, ev, resolver, types)
        elif query.star:
            proj_cols = list(range(len(names)))
        else:
            proj_cols = [resolver(p.expr.name)
                         for p in query.projections]
    except _Fallback:
        stats["fallback"] += 1
        rw.rewind()
        return None

    stats["fast"] += 1
    rw.commit()

    def gen() -> Iterator[bytes]:
        returned = 0
        buf = bytearray()
        limit = query.limit
        n_out = 0
        try:
            for batch in chain([first], reader):
                if (limit is not None and n_out >= limit
                        and not ev.is_aggregate):
                    break
                tbl = pa.Table.from_batches([batch])
                if mask_fn is not None:
                    mask = mask_fn(_Cols(tbl))
                    if not mask.any():
                        continue
                    if not mask.all():
                        tbl = tbl.filter(pa.array(mask))
                if tbl.num_rows == 0:
                    continue
                if ev.is_aggregate:
                    _accumulate(ev, tbl, agg_cols)
                    continue
                take = tbl.num_rows
                if limit is not None:
                    take = min(take, limit - n_out)
                    tbl = tbl.slice(0, take)
                pull = [tbl.column(i).to_pylist() for i in proj_cols]
                if query.star:
                    keys = [names[i] for i in proj_cols]
                    for row in zip(*pull):
                        rec = {k: v for k, v in zip(keys, row)
                               if v is not None}
                        buf += out.serialize(rec)
                        if len(buf) >= FLUSH:
                            returned += len(buf)
                            yield es.records_message(bytes(buf))
                            buf.clear()
                else:
                    keys = [
                        p.alias or Evaluator._auto_name(p.expr, i)
                        for i, p in enumerate(query.projections)
                    ]
                    for row in zip(*pull):
                        buf += out.serialize(dict(zip(keys, row)))
                        if len(buf) >= FLUSH:
                            returned += len(buf)
                            yield es.records_message(bytes(buf))
                            buf.clear()
                n_out += take
            if ev.is_aggregate:
                buf += out.serialize(ev.aggregate_result())
            if buf:
                returned += len(buf)
                yield es.records_message(bytes(buf))
            if req.request_progress:
                yield es.progress_message(object_size, object_size, returned)
            yield es.stats_message(object_size, object_size, returned)
            yield es.end_message()
        except SQLError as e:
            yield es.error_message("InvalidQuery", str(e))
        except pa.ArrowInvalid as e:
            yield es.error_message("InvalidQuery", f"JSON parse: {e}")

    return gen()


def _accumulate(ev: Evaluator, tbl, agg_cols) -> None:
    """Vectorized Evaluator.accumulate over a filtered batch: fills the
    evaluator's _agg_state so aggregate_result() serializes identically.

    Clean numeric batches take the vector path; a batch containing any
    non-numeric non-empty cell drops to the row engine's own per-value
    update (same _num/_cmp_pair calls), so garbage data behaves
    identically to the slow path — including SUM/AVG raising SQLError."""
    import pandas as pd

    import pyarrow.compute as pc

    cols = _Cols(tbl)
    for i, p in enumerate(ev.q.projections):
        f = p.expr
        st = ev._agg_state[i]
        if f.star:
            st["count"] += tbl.num_rows
            continue
        arrow = cols.arrow_nums(agg_cols[i])
        if arrow is not None:  # clean batch: every cell numeric, stay in C++
            valid = len(arrow) - arrow.null_count  # JSON missing keys
            if valid == 0:
                continue
            st["count"] += valid
            if f.name in ("sum", "avg"):
                st["sum"] += float(pc.sum(arrow).as_py())
            if f.name in ("min", "max"):
                mm = pc.min_max(arrow).as_py()
                s_col = tbl.column(agg_cols[i])
                lo = _num(s_col[pc.index(arrow, mm["min"]).as_py()].as_py())
                hi = _num(s_col[pc.index(arrow, mm["max"]).as_py()].as_py())
                if st["min"] is None:
                    st["min"], st["max"] = lo, hi
                else:
                    a, b = _cmp_pair(lo, st["min"])
                    if a < b:
                        st["min"] = lo
                    a, b = _cmp_pair(hi, st["max"])
                    if a > b:
                        st["max"] = hi
            continue
        s = cols.text(agg_cols[i])
        nonempty = s.notna().to_numpy() & (s != "").to_numpy()
        valid = int(nonempty.sum())
        if valid == 0:
            continue
        vals = s[nonempty]
        num = pd.to_numeric(vals, errors="coerce")
        if num.notna().all():
            st["count"] += valid
            if f.name in ("sum", "avg"):
                st["sum"] += float(num.sum())
            if f.name in ("min", "max"):
                # take the extreme element's OWN textual parse (first
                # occurrence), so "5" stays int and "5.0" stays float
                # exactly as the row engine's sequential _num updates
                lo = _num(vals.loc[num.idxmin()])
                hi = _num(vals.loc[num.idxmax()])
                if st["min"] is None:
                    st["min"], st["max"] = lo, hi
                else:
                    a, b = _cmp_pair(lo, st["min"])
                    if a < b:
                        st["min"] = lo
                    a, b = _cmp_pair(hi, st["max"])
                    if a > b:
                        st["max"] = hi
            continue
        # garbage batch: faithful sequential update via the row engine's
        # own coercion helpers
        for v in vals:
            st["count"] += 1
            nv = _num(v)
            if f.name in ("sum", "avg"):
                if not isinstance(nv, (int, float)) or isinstance(nv, bool):
                    raise SQLError(f"{f.name.upper()} over non-number")
                st["sum"] += nv
            if f.name in ("min", "max"):
                if st["min"] is None:
                    st["min"] = st["max"] = nv
                else:
                    a, b = _cmp_pair(nv, st["min"])
                    if a < b:
                        st["min"] = nv
                    a, b = _cmp_pair(nv, st["max"])
                    if a > b:
                        st["max"] = nv
