"""Per-tenant QoS: weighted fair admission + bandwidth isolation.

The deadline/brownout plane (ISSUE 3) sheds *global* overload, but a
single hot bucket or access key could still monopolize the one API
semaphore and starve every quiet tenant — the reference stops at a
global per-node request cap (cmd/handler-api.go).  This plane replaces
that single semaphore with a **weighted deficit-round-robin scheduler**
(ISSUE 13):

* requests classify into tenants — an explicit ``key:<access-key>``
  rule wins over the request's bucket (``bucket:<name>``), and
  bucketless/anonymous requests ride the ``default`` class;
* each tenant owns a bounded FIFO queue (a FULL tenant queue sheds 503
  for THAT tenant while every other tenant keeps flowing), a deficit
  counter, an optional concurrency cap, and an optional data-plane
  bandwidth bucket (utils/bandwidth.py TokenBucket, generalized from
  the replication limiter);
* a fixed pool of global slots (api.requests_max, same sizing as the
  old semaphore) is granted by a DRR dispatch sweep that runs
  synchronously on every release.

The admit/release/reweight/shed protocol is specified first as an
executable model (analysis/concurrency/models/qos.py, per the PR 10
convention) and this implementation mirrors it action for action:
quantum tops up once per visit and only when the queue head is not yet
affordable, a drained queue forfeits its deficit, and a reweight clamps
stale credit.

Scheduler cost is weighted by ESTIMATED BYTES (ISSUE 14 satellite,
closing the PR 13 leftover): a request's admission spends
``clamp(ceil(content_length / cost_unit), 1, max_cost)`` deficit
instead of a flat 1, so one multipart PUT is priced honestly against N
small GETs.  Requests without a body (GETs — the response size is
unknown at admission) cost 1.  A top-up that does not yet afford a
heavy head still counts as sweep progress (the model's
save-up-not-progress mutation is the wedge this prevents: a request
costing more than its tenant's weight must be able to finish saving
across sweep rounds).  ``MINIO_TPU_QOS_COST_UNIT=0`` restores flat
unit pricing.

Threading: admission calls (try_admit / enqueue / abandon / release)
run on the aiohttp event loop, exactly like the semaphore they
replace.  ``_mu`` exists for the two cross-thread surfaces — admin
reconfigure (executor thread) and metrics scrapes — and is never held
across an await.

Knobs (env wins over the dynamic ``qos`` config subsystem):
``MINIO_TPU_QOS`` gates the plane (default 0: the legacy
single-semaphore path runs byte- and metrics-identical),
``MINIO_TPU_QOS_TENANTS`` (JSON rules), ``MINIO_TPU_QOS_MAX_QUEUE``,
``MINIO_TPU_QOS_DEFAULT_WEIGHT``, ``MINIO_TPU_QOS_DEFAULT_BANDWIDTH``,
``MINIO_TPU_QOS_DEFAULT_MAX_CONCURRENCY``,
``MINIO_TPU_QOS_COST_UNIT`` (bytes per deficit point, default 1 MiB;
0 = flat unit pricing), ``MINIO_TPU_QOS_MAX_COST`` (clamp, default 32).
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import threading
import time
from collections import deque

from minio_tpu.utils.bandwidth import BandwidthMonitor, TokenBucket

#: idle tenant states (no queue, no inflight, no recent traffic) age
#: out so per-bucket auto-tenancy cannot grow the map unboundedly
IDLE_TTL_S = 900.0

#: weights below this are clamped: a zero/negative weight would starve
#: its own tenant by construction, which the no-starvation invariant
#: (models/qos.py) forbids for admitted rules
MIN_WEIGHT = 0.01

#: byte-cost pricing defaults: 1 deficit point per MiB of declared
#: body, clamped to [1, 32] so an attacker-sized Content-Length cannot
#: make its own tenant save forever (and bounds the sweep's save-up
#: rounds at max_cost / MIN_WEIGHT)
DEFAULT_COST_UNIT = 1 << 20
DEFAULT_MAX_COST = 32.0


class TenantQueueFull(Exception):
    """Arrival against a tenant queue standing at its bound — shed
    THIS tenant with 503 SlowDown; other tenants are unaffected."""


class TenantRule:
    """Admin-settable per-tenant parameters (a missing field falls back
    to the default class)."""

    __slots__ = ("weight", "max_concurrency", "bandwidth", "hot_cap")

    def __init__(self, weight: float = 1.0, max_concurrency: int = 0,
                 bandwidth: int = 0, hot_cap: int = 0):
        # NaN poisons the deficit arithmetic (deficit >= 1.0 is never
        # True — total tenant starvation from one config typo) and
        # int(inf) raises: non-finite values degrade to the neutral
        # defaults instead
        w = float(weight)
        if not math.isfinite(w):
            w = 1.0
        self.weight = max(w, MIN_WEIGHT)
        mc = float(max_concurrency)
        self.max_concurrency = max(int(mc), 0) if math.isfinite(mc) \
            else 0
        bw = float(bandwidth)
        self.bandwidth = max(int(bw), 0) if math.isfinite(bw) else 0
        # per-tenant hot-lane slot cap (ISSUE 18 satellite): 0 = fall
        # back to the plane-level hot_share bound
        hc = float(hot_cap)
        self.hot_cap = max(int(hc), 0) if math.isfinite(hc) else 0

    def to_dict(self) -> dict:
        return {"weight": self.weight,
                "max_concurrency": self.max_concurrency,
                "bandwidth": self.bandwidth,
                "hot_cap": self.hot_cap}

    @classmethod
    def from_dict(cls, doc: dict, default: "TenantRule") -> "TenantRule":
        return cls(
            weight=doc.get("weight", default.weight),
            max_concurrency=doc.get("max_concurrency",
                                    default.max_concurrency),
            bandwidth=doc.get("bandwidth", default.bandwidth),
            hot_cap=doc.get("hot_cap", default.hot_cap))


class _TenantState:
    """Scheduler-side view of one tenant: queue + deficit + counters."""

    __slots__ = ("key", "rule", "queue", "inflight", "deficit",
                 "admitted", "shed_full", "shed_deadline", "hot_admits",
                 "hot_rejects", "hot_inflight", "hot_capped",
                 "throttled_in", "throttled_out", "bw", "last_active")

    def __init__(self, key: str, rule: TenantRule):
        self.key = key
        self.rule = rule
        self.queue: deque = deque()   # asyncio futures, FIFO
        self.inflight = 0
        self.deficit = 0.0
        self.admitted = 0
        self.shed_full = 0
        self.shed_deadline = 0
        self.hot_admits = 0
        self.hot_rejects = 0
        self.hot_inflight = 0   # hot-lane slots this tenant HOLDS
        self.hot_capped = 0     # hot-lane claims refused at the cap
        self.throttled_in = 0
        self.throttled_out = 0
        self.bw = TokenBucket(rule.bandwidth) if rule.bandwidth > 0 \
            else None
        self.last_active = time.monotonic()

    def apply_rule(self, rule: TenantRule) -> None:
        """Admin reweight/recap/relimit, effective immediately: the
        deficit clamps to the new weight (models/qos.py
        reweight-keeps-stale-deficit) and the bandwidth bucket rebuilds
        only when the limit actually changed (an unchanged bucket keeps
        its debt so a reconfigure can't be used to reset pacing)."""
        old = self.rule
        self.rule = rule
        self.deficit = min(self.deficit, rule.weight)
        if rule.bandwidth != old.bandwidth or (
                self.bw is None and rule.bandwidth > 0):
            self.bw = TokenBucket(rule.bandwidth) \
                if rule.bandwidth > 0 else None

    def depth(self) -> int:
        return sum(1 for f in self.queue if not f.done())


class QosPlane:
    """The weighted-DRR admission scheduler + per-tenant bandwidth
    plane.  One instance per S3Server, replacing ``self.sem`` when
    MINIO_TPU_QOS is on."""

    def __init__(self, max_concurrency: int, *,
                 default_rule: TenantRule | None = None,
                 rules: dict[str, TenantRule] | None = None,
                 max_queue: int = 0,
                 cost_unit: int | None = None,
                 max_cost: float | None = None):
        self.max_concurrency = max(int(max_concurrency), 1)
        self.default_rule = default_rule or TenantRule()
        self.rules: dict[str, TenantRule] = dict(rules or {})
        # byte-cost pricing: bytes per deficit point (0 = flat unit
        # cost) and the [1, max_cost] clamp
        self.cost_unit = DEFAULT_COST_UNIT if cost_unit is None \
            else max(int(cost_unit), 0)
        self.max_cost = DEFAULT_MAX_COST if max_cost is None \
            else max(float(max_cost), 1.0)
        # per-tenant shed threshold; auto = 2x the slot pool (the old
        # plane queued unboundedly per-budget — the bound is what makes
        # one tenant's backlog finite)
        self.max_queue = int(max_queue) if max_queue > 0 \
            else max(16, 2 * self.max_concurrency)
        # per-tenant hot-lane cap (ISSUE 16 satellite): the hot lane
        # (app.hot_sem, sized max(max_concurrency, 4) * 2) is a SHARED
        # pool — without a per-tenant bound a hot-tenant flood of RAM
        # hits crowds the lane itself and other tenants' hits queue
        # behind drive-bound work.  Each tenant may hold at most
        # hot_share of the lane; at-cap claims fall through to normal
        # QoS admission (counted hotLaneCapped).
        self.hot_capacity = max(self.max_concurrency, 4) * 2
        self.hot_share = 0.5
        self.monitor = BandwidthMonitor()
        self._mu = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}
        self._active = 0        # granted slots (== sum of inflight)
        self._queued = 0        # live waiters across ALL tenant queues:
        # maintained at the future lifecycle level (inc on enqueue, dec
        # exactly once at grant or pending-abandon) so the aggregate
        # brownout signal is O(1) per enqueue instead of a scan of
        # every tenant's queue under the lock
        self._rr = 0            # rotation origin for the dispatch sweep
        self._rounds = 0        # DRR rotation rounds swept
        self._external = 0      # slots held by the PREVIOUS plane's
        # in-flight requests at a runtime gate flip (seed_external)
        self._last_gc = time.monotonic()
        self._loop = None       # event loop, learned at first enqueue
        # generation counter, bumped on every reconfigure: the overload
        # controller (server/controller.py) pins the generation it
        # sampled and refuses to act when an admin write moved it —
        # the never-acts-on-a-stale-snapshot invariant, live
        self.reconfigures = 0

    # -- construction --------------------------------------------------------
    @staticmethod
    def gate_enabled(config=None, environ=None) -> bool:
        """MINIO_TPU_QOS env wins; else the ``qos.enable`` config key."""
        env = os.environ if environ is None else environ
        v = env.get("MINIO_TPU_QOS")
        if v is not None:
            return v.strip().lower() not in ("", "0", "off", "false", "no")
        if config is None:
            return False
        return config.get_bool("qos", "enable", False)

    @classmethod
    def from_config(cls, config, max_concurrency: int,
                    environ=None) -> "QosPlane | None":
        if not cls.gate_enabled(config, environ):
            return None
        plane = cls(max_concurrency)
        plane.load_config(config, environ)
        return plane

    @staticmethod
    def _parse_rules(raw: str, default: TenantRule) -> dict:
        """Tenant-rule JSON -> {key: TenantRule}; malformed input
        degrades to no rules (boot must not fail on a typo'd knob)."""
        try:
            doc = json.loads(raw or "{}")
            if not isinstance(doc, dict):
                return {}
            return {str(k): TenantRule.from_dict(v, default)
                    for k, v in doc.items() if isinstance(v, dict)}
        except (ValueError, TypeError):
            return {}

    def load_config(self, config, environ=None) -> None:
        """(Re)read weights/caps/limits from env + the ``qos`` config
        subsystem and apply them to live tenant states — the dynamic
        half of the admin surface (no restart)."""
        env = os.environ if environ is None else environ

        def knob(env_key: str, cfg_key: str) -> str:
            v = env.get(env_key)
            return v if v is not None else (
                config.get("qos", cfg_key) if config is not None else "")

        def num(text: str, fallback: float) -> float:
            try:
                return float(text)
            except (TypeError, ValueError):
                return fallback

        default = TenantRule(
            weight=num(knob("MINIO_TPU_QOS_DEFAULT_WEIGHT",
                            "default_weight"), 1.0),
            max_concurrency=int(num(
                knob("MINIO_TPU_QOS_DEFAULT_MAX_CONCURRENCY",
                     "default_max_concurrency"), 0)),
            bandwidth=int(num(knob("MINIO_TPU_QOS_DEFAULT_BANDWIDTH",
                                   "default_bandwidth"), 0)),
            hot_cap=int(num(knob("MINIO_TPU_QOS_DEFAULT_HOT_CAP",
                                 "default_hot_cap"), 0)))
        rules = self._parse_rules(
            knob("MINIO_TPU_QOS_TENANTS", "tenants"), default)
        mq_raw = knob("MINIO_TPU_QOS_MAX_QUEUE", "max_queue")
        max_queue = int(num(mq_raw, 0)) if mq_raw not in ("", "auto") \
            else 0
        cu_raw = knob("MINIO_TPU_QOS_COST_UNIT", "cost_unit")
        cost_unit = None if cu_raw in ("", None) \
            else max(int(num(cu_raw, DEFAULT_COST_UNIT)), 0)
        mc_raw = knob("MINIO_TPU_QOS_MAX_COST", "max_cost")
        max_cost = None if mc_raw in ("", None) \
            else max(num(mc_raw, DEFAULT_MAX_COST), 1.0)
        hs_raw = knob("MINIO_TPU_QOS_HOT_SHARE", "hot_share")
        hot_share = None if hs_raw in ("", None) \
            else min(max(num(hs_raw, 0.5), 0.01), 1.0)
        self.reconfigure(default_rule=default, rules=rules,
                         max_queue=max_queue, cost_unit=cost_unit,
                         max_cost=max_cost, hot_share=hot_share)

    def reconfigure(self, *, default_rule: TenantRule | None = None,
                    rules: dict[str, TenantRule] | None = None,
                    max_queue: int = 0,
                    cost_unit: int | None = None,
                    max_cost: float | None = None,
                    hot_share: float | None = None) -> None:
        """Apply a new rule set atomically; live tenant states pick up
        their new weight/cap/bandwidth immediately (deficit clamped)."""
        with self._mu:
            if default_rule is not None:
                self.default_rule = default_rule
            if rules is not None:
                self.rules = dict(rules)
            self.max_queue = int(max_queue) if max_queue > 0 \
                else max(16, 2 * self.max_concurrency)
            if cost_unit is not None:
                self.cost_unit = max(int(cost_unit), 0)
            if max_cost is not None and math.isfinite(float(max_cost)):
                self.max_cost = max(float(max_cost), 1.0)
            if hot_share is not None:
                self.hot_share = min(max(float(hot_share), 0.01), 1.0)
            for st in self._tenants.values():
                st.apply_rule(self.rules.get(st.key, self.default_rule))
            self.reconfigures += 1
            loop = self._loop
        # a raised cap/weight can make parked waiters eligible NOW:
        # kick a dispatch sweep on the event loop (reconfigure runs on
        # an executor thread and futures resolve only on the loop)
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(self._dispatch_on_loop)
            except RuntimeError:
                pass  # loop shut down between the check and the call

    def _dispatch_on_loop(self) -> None:
        with self._mu:
            self._dispatch_locked()

    # -- classification ------------------------------------------------------
    @staticmethod
    def access_key_of(request) -> str:
        """CLAIMED access key, parsed cheaply pre-auth (classification
        must not cost a signature verification; weights are advisory
        scheduling state, and the signature still verifies in the
        handler)."""
        auth = request.headers.get("Authorization", "")
        if auth.startswith("AWS4-"):
            i = auth.find("Credential=")
            if i >= 0:
                cred = auth[i + len("Credential="):]
                return cred.split("/", 1)[0].split(",", 1)[0]
        elif auth.startswith("AWS "):
            return auth[4:].split(":", 1)[0]
        q = request.rel_url.query
        cred = q.get("X-Amz-Credential", "")
        if cred:
            return cred.split("/", 1)[0]
        return q.get("AWSAccessKeyId", "")

    def cost_of(self, request) -> float:
        """Admission cost of a request, weighted by its DECLARED body
        size: clamp(ceil(content_length / cost_unit), 1, max_cost).
        GETs (no body — the response size is unknown pre-admission) and
        sub-unit bodies cost 1; the clamp bounds both an attacker-sized
        Content-Length and the sweep's save-up rounds.  cost_unit=0
        restores flat unit pricing."""
        if self.cost_unit <= 0:
            return 1.0
        try:
            n = request.content_length or 0
        except (TypeError, ValueError):
            n = 0
        if n <= self.cost_unit:
            return 1.0
        return float(min(self.max_cost,
                         -(-int(n) // self.cost_unit)))

    def classify(self, request) -> str:
        """Tenant identity: explicit ``key:`` rule > the request's
        bucket (every bucket is its own tenant under the default class)
        > the ``default`` class for bucketless/anonymous requests."""
        ak = self.access_key_of(request)
        if ak:
            key = f"key:{ak}"
            if key in self.rules:
                return key
        bucket = request.match_info.get("bucket", "")
        if bucket:
            return f"bucket:{bucket}"
        return "default"

    # -- scheduler (event-loop callers) --------------------------------------
    def _state_locked(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = _TenantState(tenant,
                              self.rules.get(tenant, self.default_rule))
            self._tenants[tenant] = st
        st.last_active = time.monotonic()
        return st

    def _prune_locked(self, st: _TenantState) -> None:
        """Drop abandoned (timed-out / disconnected) waiters from the
        queue front and release forfeited deficit when it empties.
        Lives on the plane (not the tenant state) because removing a
        future from a queue is the ONE place the aggregate _queued
        counter decrements — single-owner accounting, so a future
        cancelled by wait_for before abandon() runs still pairs its
        enqueue increment exactly once."""
        q = st.queue
        while q and q[0].done():
            q.popleft()
            self._queued -= 1
        if not q:
            st.deficit = 0.0

    @staticmethod
    def _under_cap(st: _TenantState) -> bool:
        cap = st.rule.max_concurrency
        return cap <= 0 or st.inflight < cap

    def try_admit(self, tenant: str, cost: float = 1.0) -> bool:
        """Fast path: a free slot, an under-cap tenant and an empty
        tenant queue admit without queueing (the model's direct-admit
        arrival; mirrors the old `not sem.locked()` branch so an idle
        server never counts spurious pressure).  Direct admits bypass
        the deficit (as modeled) — cost prices CONTENDED admissions,
        where fairness is decided."""
        with self._mu:
            self._gc_locked()
            st = self._state_locked(tenant)
            self._prune_locked(st)
            if self._active < self.max_concurrency \
                    and self._under_cap(st) and not st.queue:
                self._active += 1
                st.inflight += 1
                st.admitted += 1
                return True
            return False

    def enqueue(self, tenant: str, cost: float = 1.0):
        """Join the tenant's admission queue.  Returns (future,
        aggregate_depth) — the aggregate cross-tenant depth feeds
        brownout pressure.  Raises TenantQueueFull at the bound.  The
        byte-estimated cost rides the future itself; the dispatch sweep
        spends it from the tenant's deficit at admission."""
        loop = asyncio.get_running_loop()
        with self._mu:
            self._loop = loop
            st = self._state_locked(tenant)
            self._prune_locked(st)
            if st.depth() >= self.max_queue:
                st.shed_full += 1
                raise TenantQueueFull(tenant)
            fut = loop.create_future()
            fut._qos_cost = max(float(cost), 1.0)
            st.queue.append(fut)
            self._queued += 1
            depth = self._queued
        return fut, depth

    def abandon(self, tenant: str, fut, *, deadline: bool = False) -> None:
        """A queued waiter left (budget expiry / client disconnect):
        drop it and, when the queue empties, forfeit the deficit —
        exactly the model's budget-expires dequeue."""
        with self._mu:
            st = self._tenants.get(tenant)
            if st is None:
                return
            if not fut.done():
                fut.cancel()
            try:
                st.queue.remove(fut)
                self._queued -= 1  # single-owner: we removed it
            except ValueError:
                pass  # already popped (granted or pruned): counted there
            self._prune_locked(st)
            if deadline:
                st.shed_deadline += 1

    def release(self, tenant: str) -> None:
        """A granted request finished: free the slot and run the DRR
        dispatch sweep (the protocol's release action — skipping the
        sweep is the model's release-skips-dispatch mutation)."""
        with self._mu:
            st = self._tenants.get(tenant)
            if st is not None and st.inflight > 0:
                st.inflight -= 1
            self._active = max(0, self._active - 1)
            self._dispatch_locked()

    @staticmethod
    def _head_cost(st: _TenantState) -> float:
        """Cost of the tenant's queue head (1.0 for legacy futures)."""
        return getattr(st.queue[0], "_qos_cost", 1.0)

    def _dispatch_locked(self) -> None:
        """The DRR sweep over nonempty queues: quantum once per visit
        (only when the head is not yet affordable), spend the head's
        BYTE COST per admission, stop at the slot pool / tenant cap /
        drained queue / unaffordable head, forfeit deficit on empty.
        A top-up that does not yet afford a heavy head still counts as
        progress — a request costing more than its tenant's weight
        saves up across rounds instead of stranding (models/qos.py
        save-up-not-progress).  A round that admitted NOTHING (every
        servable tenant is saving) fast-forwards the remaining save-up
        rounds arithmetically — each saver gains k·weight where k is
        the fewest rounds until some head becomes affordable, exactly
        what k literal rounds would produce — so the sweep never spins
        cost/weight iterations under the plane mutex on the event loop
        (a hostile Content-Length with a tiny weight would otherwise
        stall the server).  Mirrors models/qos.py `_dispatch` (the
        fast-forward is state-identical to the model's literal
        rounds)."""
        progress = True
        while progress and self._active < self.max_concurrency:
            progress = False
            admitted_this_round = False
            savers: list[_TenantState] = []
            order = sorted(k for k, t in self._tenants.items() if t.queue)
            if not order:
                return
            self._rounds += 1
            n = len(order)
            start = self._rr % n
            for off in range(n):
                st = self._tenants[order[(start + off) % n]]
                self._prune_locked(st)
                if st.queue and self._active < self.max_concurrency \
                        and self._under_cap(st):
                    if st.deficit < self._head_cost(st):
                        st.deficit += st.rule.weight
                        progress = True  # saving toward a heavy head
                    while st.queue \
                            and self._active < self.max_concurrency \
                            and self._under_cap(st):
                        fut = st.queue[0]
                        if fut.done():
                            st.queue.popleft()
                            self._queued -= 1  # single-owner: removed
                            continue
                        cost = getattr(fut, "_qos_cost", 1.0)
                        if st.deficit < cost:
                            break  # keep saving next visit
                        st.queue.popleft()
                        self._queued -= 1  # single-owner: we removed it
                        st.deficit -= cost
                        st.inflight += 1
                        st.admitted += 1
                        self._active += 1
                        st.last_active = time.monotonic()
                        fut.set_result(True)
                        progress = True
                        admitted_this_round = True
                    if st.queue and self._under_cap(st) \
                            and st.deficit < self._head_cost(st):
                        savers.append(st)
                if not st.queue:
                    st.deficit = 0.0
            self._rr += 1
            if progress and not admitted_this_round and savers \
                    and self._active < self.max_concurrency:
                # fast-forward: k = rounds until the cheapest saver
                # affords; each saver gains exactly what k more literal
                # rounds would grant (growth stops at affordability, so
                # the deficit bound weight + cost - 1 is preserved)
                k = min(math.ceil(
                    (self._head_cost(st) - st.deficit) / st.rule.weight)
                    for st in savers)
                if k > 1:
                    for st in savers:
                        need = math.ceil(
                            (self._head_cost(st) - st.deficit)
                            / st.rule.weight)
                        st.deficit += min(k, need) * st.rule.weight

    def _gc_locked(self) -> None:
        """Age out idle auto-tenancy states (bounded map, bounded
        work: at most once per 60 s)."""
        now = time.monotonic()
        if now - self._last_gc < 60.0:
            return
        self._last_gc = now
        for key in [k for k, t in self._tenants.items()
                    if not t.queue and t.inflight == 0
                    and t.hot_inflight == 0
                    and now - t.last_active > IDLE_TTL_S]:
            del self._tenants[key]

    def seed_external(self, n: int) -> None:
        """Account for requests the PREVIOUS admission plane (the
        legacy semaphore) already has in flight when this plane takes
        over at a runtime gate flip: they hold real executor/IO
        capacity, so the pool starts with their slots granted —
        otherwise the flip would transiently admit up to 2x
        max_concurrency and break the executor-sizing invariant that
        keeps body-feed tasks schedulable."""
        with self._mu:
            n = max(0, int(n))
            self._external = n
            self._active += n

    def external_release(self) -> None:
        """A legacy-plane request finished while this plane is live:
        free its externally-seeded slot and run the dispatch sweep."""
        with self._mu:
            if self._external <= 0:
                return
            self._external -= 1
            self._active = max(0, self._active - 1)
            self._dispatch_locked()

    def saturated(self) -> bool:
        """True when every global slot is granted — the AGGREGATE
        overload signal: sheds fired while slots were still free are a
        tenant's private bound working and must not engage brownout."""
        with self._mu:
            return self._active >= self.max_concurrency

    # -- hot-lane accounting (ISSUE 13 satellite) ----------------------------
    def hot_cap(self) -> int:
        """Plane-level per-tenant hot-lane slot bound: hot_share of
        the lane (tenants without an explicit rule cap)."""
        return max(1, int(self.hot_capacity * self.hot_share))

    def hot_cap_of(self, st: "_TenantState") -> int:
        """Effective hot-lane bound for ONE tenant (ISSUE 18
        satellite): an explicit TenantRule.hot_cap wins (clamped to
        the lane size); 0 falls back to the uniform hot_share bound,
        so existing configs behave exactly as before."""
        if st.rule.hot_cap > 0:
            return min(st.rule.hot_cap, self.hot_capacity)
        return self.hot_cap()

    def hot_lane_try(self, tenant: str) -> bool:
        """Claim one per-tenant hot-lane slot (ISSUE 16 satellite).
        False when the tenant already holds its share of the lane —
        the request pays normal QoS admission instead, so one tenant's
        flood of RAM hits can never crowd `hot_sem` itself and starve
        other tenants' hits (counted hotLaneCapped)."""
        with self._mu:
            st = self._state_locked(tenant)
            if st.hot_inflight >= self.hot_cap_of(st):
                st.hot_capped += 1
                return False
            st.hot_inflight += 1
            return True

    def hot_lane_release(self, tenant: str) -> None:
        with self._mu:
            st = self._tenants.get(tenant)
            if st is not None and st.hot_inflight > 0:
                st.hot_inflight -= 1

    def note_hot_admit(self, tenant: str) -> None:
        with self._mu:
            self._state_locked(tenant).hot_admits += 1

    def note_hot_reject(self, tenant: str) -> None:
        """A probable hit failed its post-acquire re-probe and fell
        back to the API lane: folded into per-tenant stats so hit-ratio
        and shed counters stay honest under QoS."""
        with self._mu:
            self._state_locked(tenant).hot_rejects += 1

    # -- bandwidth (data-path metering) --------------------------------------
    def bw_wait(self, tenant: str, n: int, direction: str) -> float:
        """Charge `n` data-plane bytes to the tenant's bucket and
        return the pacing debt (0.0 when unlimited/inside burst); the
        async caller awaits asyncio.sleep on it.  Every metered chunk
        also feeds the per-tenant rate monitor."""
        if n <= 0:
            return 0.0
        with self._mu:
            st = self._state_locked(tenant)
            bw = st.bw
            if direction == "in":
                st.throttled_in += n
            else:
                st.throttled_out += n
        self.monitor.record(tenant, direction, n)
        return bw.debit(n) if bw is not None else 0.0

    async def throttle(self, tenant: str, n: int, direction: str) -> None:
        wait = self.bw_wait(tenant, n, direction)
        if wait > 0:
            await asyncio.sleep(wait)

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        """Per-tenant live stats + plane totals (metrics + admin)."""
        with self._mu:
            tenants = {}
            for key, st in self._tenants.items():
                tenants[key] = {
                    "weight": st.rule.weight,
                    "maxConcurrency": st.rule.max_concurrency,
                    "bandwidth": st.rule.bandwidth,
                    "hotCap": self.hot_cap_of(st),
                    "inflight": st.inflight,
                    "queueDepth": st.depth(),
                    "deficit": round(st.deficit, 6),
                    "admitted": st.admitted,
                    "shedQueueFull": st.shed_full,
                    "shedDeadline": st.shed_deadline,
                    "hotLaneAdmits": st.hot_admits,
                    "hotLaneRejections": st.hot_rejects,
                    "hotLaneInflight": st.hot_inflight,
                    "hotLaneCapped": st.hot_capped,
                    "throttledInBytes": st.throttled_in,
                    "throttledOutBytes": st.throttled_out,
                }
            return {
                "maxConcurrency": self.max_concurrency,
                "maxQueue": self.max_queue,
                "costUnit": self.cost_unit,
                "maxCost": self.max_cost,
                "hotCapPerTenant": self.hot_cap(),
                "active": self._active,
                "deficitRounds": self._rounds,
                "defaults": self.default_rule.to_dict(),
                "rules": {k: r.to_dict() for k, r in self.rules.items()},
                "tenants": tenants,
            }

    def rates(self) -> dict:
        """Per-tenant moving-average bytes/sec in/out (BandwidthMonitor
        generalized from replication targets to tenants)."""
        return self.monitor.report()
